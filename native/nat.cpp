// C ABI for the native host core. Exports:
//
// - nat_prep_lanes: batch lane preparation for the TPU verify kernel —
//   the native twin of TpuSecpVerifier._prep_lanes + _pack_lanes
//   (crypto/jax_backend.py): structural pubkey parse, lax-DER, high-S
//   normalization, Montgomery-batched s^-1 mod n, BIP340 challenge
//   hashing, GLV lambda split, byte packing. One call per dispatch chunk.
// - nat_verify_{ecdsa,schnorr}, nat_tweak_add_check: full host-exact
//   single verifies (the scalar fallback path).
// - nat_sha256 / nat_sha256d / nat_tagged_hash: hashing utilities.
//
// Layouts must stay bit-identical to the Python packers; the test suite
// asserts equality lane by lane (tests/test_native.py).

#include "block.hpp"
#include "eval.hpp"
#include "secp.hpp"

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <thread>

using namespace nat;

namespace {

constexpr int KIND_ECDSA = 0;
constexpr int KIND_SCHNORR = 1;
constexpr int KIND_TWEAK = 2;

struct Lane {
    // mirrors jax_backend._Lane defaults
    bool valid = false;
    Sc a{};                    // fixed-base scalar
    u64 b1[2] = {0, 0};        // |GLV half 1| little-endian
    u64 b2[2] = {0, 0};
    i32 neg1 = 0, neg2 = 0;
    U256 px{};                 // raw x (defaults to G_X below)
    i32 want_odd = 0;
    U256 t1{};                 // raw target
    i32 has_t2 = 0;
    i32 parity = -1;
};

inline void set_b(Lane& ln, const Sc& b) {
    GlvSplit sp = split_lambda(b);
    if (!sp.ok) {  // cannot happen for k < n; defensive
        ln.valid = false;
        return;
    }
    ln.b1[0] = sp.a1[0];
    ln.b1[1] = sp.a1[1];
    ln.b2[0] = sp.a2[0];
    ln.b2[1] = sp.a2[1];
    ln.neg1 = sp.neg1;
    ln.neg2 = sp.neg2;
}

// Structural half of pubkey parsing (jax_backend._host_parse_pubkey): no
// square root for compressed keys — the y lift happens on device from
// (x, want_odd); the 65-byte form shares parse_uncompressed_pubkey with
// the host-exact verify path.
inline bool host_parse_pubkey(Lane& ln, const u8* pk, i64 len) {
    if (len == 33 && (pk[0] == 2 || pk[0] == 3)) {
        U256 x = u256_from_be(pk + 1);
        if (u256_cmp(x, FIELD_P()) >= 0) return false;
        ln.px = x;
        ln.want_odd = pk[0] == 3 ? 1 : 0;
        return true;
    }
    if (len == 65 && (pk[0] == 4 || pk[0] == 6 || pk[0] == 7)) {
        Fe x, y;
        if (!parse_uncompressed_pubkey(pk, &x, &y)) return false;
        ln.px = x.n;
        ln.want_odd = fe_is_odd(y) ? 1 : 0;
        return true;
    }
    return false;
}

// Shared bodies for the records/spec drain trios and the single/batched
// verify surfaces (one implementation, two wire paths).

void fill_records_meta(const std::vector<Record>& v, i32* kinds, i32* parities,
                       i64* lens) {
    for (size_t i = 0; i < v.size(); i++) {
        const Record& r = v[i];
        kinds[i] = r.kind;
        parities[i] = r.parity;
        lens[3 * i] = (i64)r.p0.size();
        lens[3 * i + 1] = (i64)r.p1.size();
        lens[3 * i + 2] = (i64)r.p2.size();
    }
}

i64 records_total_bytes(const std::vector<Record>& v) {
    i64 total = 0;
    for (const Record& r : v)
        total += (i64)(r.p0.size() + r.p1.size() + r.p2.size());
    return total;
}

void fill_records_data(const std::vector<Record>& v, u8* blob) {
    size_t pos = 0;
    for (const Record& r : v) {
        std::memcpy(blob + pos, r.p0.data(), r.p0.size());
        pos += r.p0.size();
        std::memcpy(blob + pos, r.p1.data(), r.p1.size());
        pos += r.p1.size();
        std::memcpy(blob + pos, r.p2.data(), r.p2.size());
        pos += r.p2.size();
    }
}

// One input through verify_script with a (possibly deferring) checker;
// bounds-checks n_in. Does NOT touch the session's records/unknown state —
// callers own the clear/boundary bookkeeping.
i32 run_verify_input(Session* sess, NTx* tx, i32 n_in, i64 amount,
                     const u8* spk, i64 spk_len, i32 flags, i32 mode,
                     i32* script_err, i32* unknown) {
    if (n_in < 0 || (size_t)n_in >= tx->vin.size()) {
        *script_err = SE_UNKNOWN_ERROR;
        *unknown = 0;
        return 0;
    }
    if (sess) sess->unknown = 0;
    Checker checker;
    checker.tx = tx;
    checker.n_in = (size_t)n_in;
    checker.amount = amount;
    checker.mode = mode;
    checker.sess = sess;
    Bytes spk_b(spk, spk + spk_len);
    EvalResult r = verify_script(tx->vin[(size_t)n_in].script_sig, spk_b,
                                 tx->vin[(size_t)n_in].witness, (u32)flags,
                                 checker);
    *script_err = r.err;
    *unknown = sess ? sess->unknown : 0;
    return r.ok ? 1 : 0;
}

// --- Reference-compatible libbitcoinconsensus ABI -------------------------
// Drop-in twins of the reference's three exported symbols
// (bitcoinconsensus.h:67-75): same signatures, same error enum
// (bitcoinconsensus.h:38-46), same check ordering (flags -> deserialize ->
// index -> size, bitcoinconsensus.cpp:79-102). Consumers that link
// libbitcoinconsensus can link libnat instead; tests/test_drop_in_abi.py
// replays the differential corpus through BOTH .so's via one ctypes path.

constexpr i32 BC_ERR_OK = 0;
constexpr i32 BC_ERR_TX_INDEX = 1;
constexpr i32 BC_ERR_TX_SIZE_MISMATCH = 2;
constexpr i32 BC_ERR_TX_DESERIALIZE = 3;
constexpr i32 BC_ERR_AMOUNT_REQUIRED = 4;
constexpr i32 BC_ERR_INVALID_FLAGS = 5;

// bitcoinconsensus_SCRIPT_FLAGS_VERIFY_ALL (bitcoinconsensus.h:49-61):
// P2SH | DERSIG | NULLDUMMY | CHECKLOCKTIMEVERIFY | CHECKSEQUENCEVERIFY |
// WITNESS. Anything outside is rejected (verify_flags,
// bitcoinconsensus.cpp:74-77).
constexpr u32 BC_FLAGS_VERIFY_ALL =
    (1u << 0) | (1u << 2) | (1u << 4) | (1u << 9) | (1u << 10) | (1u << 11);

inline int bc_set_error(i32* err, i32 code) {
    if (err) *err = code;
    return 0;
}

int bc_verify(const u8* spk, u32 spk_len, i64 amount, const u8* tx_to,
              u32 tx_to_len, u32 n_in, u32 flags, i32* err) {
    if (flags & ~BC_FLAGS_VERIFY_ALL)
        return bc_set_error(err, BC_ERR_INVALID_FLAGS);
    try {
        std::unique_ptr<NTx> tx(tx_parse(tx_to, (size_t)tx_to_len));
        if (n_in >= tx->vin.size()) return bc_set_error(err, BC_ERR_TX_INDEX);
        // Exact re-serialization check (bitcoinconsensus.cpp:91-92):
        // trailing bytes or non-canonical encodings that still parse must
        // report TX_SIZE_MISMATCH.
        if (tx->ser_size != (i64)tx_to_len)
            return bc_set_error(err, BC_ERR_TX_SIZE_MISMATCH);
        // Regardless of the verification result, the tx did not error
        // (bitcoinconsensus.cpp:94-95).
        bc_set_error(err, BC_ERR_OK);
        precompute(*tx, nullptr);
        i32 script_err, unknown;
        return run_verify_input(nullptr, tx.get(), (i32)n_in, amount, spk,
                                (i64)spk_len, (i32)flags, MODE_EXACT,
                                &script_err, &unknown);
    } catch (...) {
        // Same fence as the reference shim (bitcoinconsensus.cpp:99-101).
        return bc_set_error(err, BC_ERR_TX_DESERIALIZE);
    }
}

}  // namespace

extern "C" {

// 4: nat_session_recidx_data grew a capacity argument + i64 return;
//    the nat_block_* / nat_view_* block layer landed.
int nat_version() { return 4; }

// --- Block layer (native/block.hpp) ---------------------------------------

void* nat_block_parse(const u8* data, i64 len) {
    try {
        return block_parse(data, (size_t)len);
    } catch (...) {
        return nullptr;
    }
}

void nat_block_free(void* b) { delete static_cast<NBlock*>(b); }

i32 nat_block_n_tx(void* b) {
    return (i32)static_cast<NBlock*>(b)->vtx.size();
}

// Total non-coinbase inputs (the script-phase lane count).
i32 nat_block_n_inputs(void* b) {
    auto* blk = static_cast<NBlock*>(b);
    i64 n = 0;
    for (const auto& tx : blk->vtx)
        if (!tx_is_coinbase(*tx)) n += (i64)tx->vin.size();
    return (i32)n;
}

// Borrowed pointer into the block (freed with the block, never by
// nat_tx_free).
void* nat_block_tx(void* b, i32 i) {
    auto* blk = static_cast<NBlock*>(b);
    if (i < 0 || (size_t)i >= blk->vtx.size()) return nullptr;
    return blk->vtx[(size_t)i].get();
}

void nat_block_txid(void* b, i32 i, u8* out32) {
    auto* blk = static_cast<NBlock*>(b);
    std::memcpy(out32, blk->txids[(size_t)i].data(), 32);
}

void nat_block_wtxid(void* b, i32 i, u8* out32) {
    auto* blk = static_cast<NBlock*>(b);
    std::memcpy(out32, blk->wtxids[(size_t)i].data(), 32);
}

// Context-free CheckBlock; returns a BlkReason code (0 = ok).
i32 nat_block_check(void* b, i32 do_pow, const u8* pow_limit_be,
                    i32 do_merkle) {
    return check_block(*static_cast<NBlock*>(b), do_pow != 0, pow_limit_be,
                       do_merkle != 0);
}

i32 nat_block_check_witness(void* b) {
    return check_witness_commitment(*static_cast<NBlock*>(b));
}

i32 nat_block_accounting(void* b, void* v, i64 height, i32 flags) {
    return block_accounting(*static_cast<NBlock*>(b),
                            *static_cast<NView*>(v), height, (u32)flags);
}

void nat_block_acct_meta(void* b, i64* fees, i64* sigop_cost, i64* n_inputs,
                         i64* spk_bytes) {
    const BlockAcct& A = static_cast<NBlock*>(b)->acct;
    *fees = A.fees;
    *sigop_cost = A.sigop_cost;
    *n_inputs = (i64)A.tx_index.size();
    *spk_bytes = (i64)A.spk_blob.size();
}

void nat_block_acct_data(void* b, i32* tx_index, i32* n_in, i64* amounts,
                         i64* spk_offs, u8* spk_blob) {
    const BlockAcct& A = static_cast<NBlock*>(b)->acct;
    size_t n = A.tx_index.size();
    if (n) {
        std::memcpy(tx_index, A.tx_index.data(), n * sizeof(i32));
        std::memcpy(n_in, A.n_in.data(), n * sizeof(i32));
        std::memcpy(amounts, A.amounts.data(), n * sizeof(i64));
    }
    std::memcpy(spk_offs, A.spk_offs.data(), (n + 1) * sizeof(i64));
    if (!A.spk_blob.empty())
        std::memcpy(spk_blob, A.spk_blob.data(), A.spk_blob.size());
}

// Per-tx spent-output digests (models/sigcache.py spent_digest stream);
// coinbase rows are zero. out: n_tx * 32 bytes.
void nat_block_spent_digests(void* b, u8* out) {
    const BlockAcct& A = static_cast<NBlock*>(b)->acct;
    for (size_t t = 0; t < A.spent_digests.size(); t++)
        std::memcpy(out + 32 * t, A.spent_digests[t].data(), 32);
}

// Script-execution-cache keys for every non-coinbase input (valid after
// accounting): the models/sigcache.py `_key(_parts(wtxid, n_in, flags,
// spent_digest))` stream — sha256(salt || [len(part) 4LE || part]...)
// with parts (wtxid32, n_in 4LE, flags 4LE, digest32). out: n_inputs*32.
void nat_block_script_keys(void* b, const u8* salt, i64 salt_len, i32 flags,
                           u8* out) {
    auto* blk = static_cast<NBlock*>(b);
    const BlockAcct& A = blk->acct;
    auto part = [](Sha256& h, const u8* p, u32 len) {
        u8 lb[4] = {u8(len), u8(len >> 8), u8(len >> 16), u8(len >> 24)};
        h.write(lb, 4);
        h.write(p, len);
    };
    u8 f4[4] = {u8(flags), u8(flags >> 8), u8(flags >> 16), u8(flags >> 24)};
    // One midstate per (salt); wtxid/digest swap per tx.
    for (size_t j = 0; j < A.tx_index.size(); j++) {
        i32 t = A.tx_index[j];
        Sha256 h;
        h.write(salt, (size_t)salt_len);
        part(h, blk->wtxids[(size_t)t].data(), 32);
        i32 n = A.n_in[j];
        u8 n4[4] = {u8(n), u8(n >> 8), u8(n >> 16), u8(n >> 24)};
        part(h, n4, 4);
        part(h, f4, 4);
        part(h, A.spent_digests[(size_t)t].data(), 32);
        h.finalize(out + 32 * j);
    }
}

void* nat_view_new() { return new NView(); }

void nat_view_free(void* v) { delete static_cast<NView*>(v); }

void* nat_view_clone(void* v) {
    return new NView(*static_cast<NView*>(v));
}

i64 nat_view_len(void* v) {
    return (i64)static_cast<NView*>(v)->map.size();
}

// Batch coin insert: coin i is (txids[32i..32i+32), ns[i]) ->
// (values[i], heights[i], coinbases[i], spk_blob[spk_offs[i]..spk_offs[i+1])).
void nat_view_add_coins(void* v, i32 n, const u8* txids, const i32* ns,
                        const i64* values, const i32* heights,
                        const i32* coinbases, const u8* spk_blob,
                        const i64* spk_offs) {
    auto* view = static_cast<NView*>(v);
    for (i32 i = 0; i < n; i++) {
        NCoin c;
        c.value = values[i];
        c.height = heights[i];
        c.coinbase = coinbases[i] != 0;
        c.spk.assign(spk_blob + spk_offs[i], spk_blob + spk_offs[i + 1]);
        view->map[NView::key(txids + 32 * (size_t)i, (u32)ns[i])] =
            std::move(c);
    }
}

// Point query: returns 1 if present (filling value/height/coinbase/spk_len),
// else 0. The scriptPubKey bytes follow via nat_view_get_spk.
i32 nat_view_get(void* v, const u8* txid, i32 n, i64* value, i32* height,
                 i32* coinbase, i64* spk_len) {
    auto* view = static_cast<NView*>(v);
    auto it = view->map.find(NView::key(txid, (u32)n));
    if (it == view->map.end()) return 0;
    *value = it->second.value;
    *height = it->second.height;
    *coinbase = it->second.coinbase ? 1 : 0;
    *spk_len = (i64)it->second.spk.size();
    return 1;
}

void nat_view_get_spk(void* v, const u8* txid, i32 n, u8* out) {
    auto* view = static_cast<NView*>(v);
    auto it = view->map.find(NView::key(txid, (u32)n));
    if (it == view->map.end()) return;
    std::memcpy(out, it->second.spk.data(), it->second.spk.size());
}

i32 nat_view_spend(void* v, const u8* txid, i32 n) {
    auto* view = static_cast<NView*>(v);
    return view->map.erase(NView::key(txid, (u32)n)) ? 1 : 0;
}

void nat_view_apply_block(void* v, void* b, i64 height) {
    view_apply_block(*static_cast<NView*>(v), *static_cast<NBlock*>(b),
                     height);
}

// The three libbitcoinconsensus exports (bitcoinconsensus.h:67-75).

int bitcoinconsensus_verify_script_with_amount(
    const unsigned char* scriptPubKey, unsigned int scriptPubKeyLen,
    int64_t amount, const unsigned char* txTo, unsigned int txToLen,
    unsigned int nIn, unsigned int flags, i32* err) {
    return bc_verify(scriptPubKey, scriptPubKeyLen, (i64)amount, txTo, txToLen,
                     nIn, flags, err);
}

int bitcoinconsensus_verify_script(const unsigned char* scriptPubKey,
                                   unsigned int scriptPubKeyLen,
                                   const unsigned char* txTo,
                                   unsigned int txToLen, unsigned int nIn,
                                   unsigned int flags, i32* err) {
    // The amount-less entry cannot serve BIP143 sighashes: WITNESS
    // requires an amount (bitcoinconsensus.cpp:115-121).
    if (flags & (1u << 11)) return bc_set_error(err, BC_ERR_AMOUNT_REQUIRED);
    return bc_verify(scriptPubKey, scriptPubKeyLen, 0, txTo, txToLen, nIn,
                     flags, err);
}

unsigned int bitcoinconsensus_version() {
    return 1;  // BITCOINCONSENSUS_API_VER (bitcoinconsensus.h:36)
}

unsigned int nat_murmur3_32(unsigned int seed, const u8* data, i64 len) {
    return murmur3_32(seed, data, (size_t)len);
}

void nat_sha256(const u8* data, i64 len, u8* out32) {
    sha256(data, (size_t)len, out32);
}

void nat_sha256d(const u8* data, i64 len, u8* out32) {
    sha256d(data, (size_t)len, out32);
}

void nat_tagged_hash(const u8* tag, i64 taglen, const u8* data, i64 len,
                     u8* out32) {
    u8 th[32];
    sha256(tag, (size_t)taglen, th);
    Sha256 h;
    h.write(th, 32);
    h.write(th, 32);
    h.write(data, (size_t)len);
    h.finalize(out32);
}

int nat_verify_ecdsa(const u8* pub, i64 publen, const u8* sig, i64 siglen,
                     const u8* msg32) {
    return verify_ecdsa(pub, (size_t)publen, sig, (size_t)siglen, msg32) ? 1 : 0;
}

int nat_verify_schnorr(const u8* pk32, const u8* sig64, const u8* msg32) {
    return verify_schnorr(pk32, sig64, msg32) ? 1 : 0;
}

int nat_tweak_add_check(const u8* tweaked32, i32 parity, const u8* internal32,
                        const u8* tweak32) {
    return tweak_add_check(tweaked32, parity, internal32, tweak32) ? 1 : 0;
}

// One check's parts, independent of where the bytes live (wire blob from
// Python or a session-resident Record) — the shared input shape of the
// lane-prep and digest cores.
struct PartsView {
    int kind;    // 0 ecdsa, 1 schnorr, 2 tweak
    int parity;  // tweak parity bit
    const u8* p0;
    i64 l0;
    const u8* p1;
    i64 l1;
    const u8* p2;
    i64 l2;
};

inline PartsView parts_from_wire(const u8* blob, const i64* offs,
                                 const i32* kinds, i32 i) {
    return PartsView{
        kinds[i] & 0xff,          (kinds[i] >> 8) & 1,
        blob + offs[3 * i],       offs[3 * i + 1] - offs[3 * i],
        blob + offs[3 * i + 1],   offs[3 * i + 2] - offs[3 * i + 1],
        blob + offs[3 * i + 2],   offs[3 * i + 3] - offs[3 * i + 2],
    };
}

// Record/digest part order (ecdsa pubkey|sig|msg, schnorr pk32|sig64|msg,
// tweak q32|internal32|tweak32 — the models/sigcache.py stream order).
inline PartsView parts_from_record(const Record& r) {
    return PartsView{
        r.kind,          r.parity,
        r.p0.data(),     (i64)r.p0.size(),
        r.p1.data(),     (i64)r.p1.size(),
        r.p2.data(),     (i64)r.p2.size(),
    };
}

// Lane-prep part order: the prep core expects tweak checks as
// internal32 | tweak32 | tweaked32 (the prep_pack wire permutation).
inline PartsView parts_from_record_lanes(const Record& r) {
    if (r.kind == KIND_TWEAK)
        return PartsView{
            r.kind,          r.parity,
            r.p1.data(),     (i64)r.p1.size(),
            r.p2.data(),     (i64)r.p2.size(),
            r.p0.data(),     (i64)r.p0.size(),
        };
    return parts_from_record(r);
}

// Lane-prep core: parts -> packed kernel lanes. Parts per kind:
//     ecdsa:   pubkey | sig_der | msg32
//     schnorr: pk32   | sig64   | msg32
//     tweak:   internal32 | tweak32 | tweaked32
// Outputs (caller-allocated, only the first n lanes are written):
//   fields: n*128 bytes — per lane (a | b1 | b2 | px | t1) little-endian
//   want_odd/parity/has_t2/neg1/neg2/valid: n x i32 each
void prep_lanes_impl(const std::vector<PartsView>& parts, u8* fields,
                     i32* want_odd, i32* parity, i32* has_t2, i32* neg1,
                     i32* neg2, i32* valid) {
    // Pass 1: parse everything; collect ECDSA (r, s, m) for the batched
    // inversion (jax_backend._batch_inv_mod_n shape: one Fermat total).
    const i32 n = (i32)parts.size();
    std::vector<Lane> lanes((size_t)n);
    std::vector<i32> ecdsa_idx((size_t)n);
    std::vector<Sc> ecdsa_r((size_t)n);
    std::vector<Sc> ecdsa_s((size_t)n);
    std::vector<Sc> ecdsa_m((size_t)n);
    i32 n_ecdsa = 0;

    for (i32 i = 0; i < n; i++) {
        Lane& ln = lanes[i];
        ln.px = GEN().x.n;  // invalid-lane default matches _Lane (G_X)
        const u8* p0 = parts[i].p0;
        i64 l0 = parts[i].l0;
        const u8* p1 = parts[i].p1;
        i64 l1 = parts[i].l1;
        const u8* p2 = parts[i].p2;
        i64 l2 = parts[i].l2;
        int kind = parts[i].kind;
        if (kind == KIND_ECDSA) {
            if (l2 != 32) continue;
            if (!host_parse_pubkey(ln, p0, l0)) continue;
            Sc r, s;
            if (!parse_der_lax(p1, (size_t)l1, &r, &s)) continue;
            if (sc_is_high(s)) s = sc_neg(s);
            if (sc_is_zero(r) || sc_is_zero(s)) continue;
            ln.t1 = r.n;
            U256 rn;
            u64 carry = u256_add(rn, r.n, ORDER_N());
            ln.has_t2 = (!carry && u256_cmp(rn, FIELD_P()) < 0) ? 1 : 0;
            ln.valid = true;
            ecdsa_idx[n_ecdsa] = i;
            ecdsa_r[n_ecdsa] = r;
            ecdsa_s[n_ecdsa] = s;
            ecdsa_m[n_ecdsa] = sc_from_be(p2);
            n_ecdsa++;
        } else if (kind == KIND_SCHNORR) {
            if (l0 != 32 || l1 != 64 || l2 != 32) continue;
            U256 px = u256_from_be(p0);
            if (u256_cmp(px, FIELD_P()) >= 0) continue;
            U256 r_u = u256_from_be(p1);
            U256 s_u = u256_from_be(p1 + 32);
            if (u256_cmp(r_u, FIELD_P()) >= 0) continue;
            if (u256_cmp(s_u, ORDER_N()) >= 0) continue;
            u8 ch_in[96];
            std::memcpy(ch_in, p1, 32);
            std::memcpy(ch_in + 32, p0, 32);
            std::memcpy(ch_in + 64, p2, 32);
            u8 e_b[32];
            BIP340_CHALLENGE().hash(ch_in, 96, e_b);
            Sc e = sc_from_be(e_b);
            ln.px = px;
            ln.want_odd = 0;
            ln.a.n = s_u;
            set_b(ln, sc_neg(e));  // (n - e) mod n
            ln.t1 = r_u;
            ln.parity = 0;
            ln.valid = true;
        } else if (kind == KIND_TWEAK) {
            if (l0 != 32 || l1 != 32 || l2 != 32) continue;
            U256 px = u256_from_be(p0);
            if (u256_cmp(px, FIELD_P()) >= 0) continue;
            U256 t_u = u256_from_be(p1);
            if (u256_cmp(t_u, ORDER_N()) >= 0) continue;
            ln.px = px;
            ln.want_odd = 0;
            ln.a.n = t_u;
            Sc one;
            one.n = {{1, 0, 0, 0}};
            set_b(ln, one);
            ln.t1 = u256_from_be(p2);  // raw: >= p can never match
            ln.parity = parts[i].parity;
            ln.valid = true;
        }
    }

    // Batched modular inverse of the ECDSA s values (Montgomery trick:
    // one Fermat chain total).
    if (n_ecdsa) {
        std::vector<Sc> prefix((size_t)n_ecdsa);
        Sc acc;
        acc.n = {{1, 0, 0, 0}};
        for (i32 j = 0; j < n_ecdsa; j++) {
            acc = sc_mul(acc, ecdsa_s[j]);
            prefix[j] = acc;
        }
        Sc inv = sc_inv(acc);
        for (i32 j = n_ecdsa - 1; j >= 0; j--) {
            Sc sinv = j ? sc_mul(inv, prefix[j - 1]) : inv;
            inv = sc_mul(inv, ecdsa_s[j]);
            Lane& ln = lanes[ecdsa_idx[j]];
            ln.a = sc_mul(ecdsa_m[j], sinv);      // u1
            set_b(ln, sc_mul(ecdsa_r[j], sinv));  // u2
        }
    }

    // Pack (jax_backend._pack_lanes layout).
    for (i32 i = 0; i < n; i++) {
        const Lane& ln = lanes[i];
        u8* f = fields + (size_t)i * 128;
        u256_to_le(ln.a.n, f);
        for (int j = 0; j < 2; j++) {
            u64 w = ln.b1[j];
            for (int k = 0; k < 8; k++) f[32 + 8 * j + k] = u8(w >> (8 * k));
            w = ln.b2[j];
            for (int k = 0; k < 8; k++) f[48 + 8 * j + k] = u8(w >> (8 * k));
        }
        u256_to_le(ln.px, f + 64);
        u256_to_le(ln.t1, f + 96);
        want_odd[i] = ln.want_odd;
        parity[i] = ln.parity;
        has_t2[i] = ln.has_t2;
        neg1[i] = ln.neg1;
        neg2[i] = ln.neg2;
        valid[i] = ln.valid ? 1 : 0;
    }
}

// Wire-shape entry (Python packs blob/offs/kinds; kinds[i]&0xff is the
// kind, bit 8 the tweak parity).
void nat_prep_lanes(const u8* blob, const i64* offs, const i32* kinds, i32 n,
                    u8* fields, i32* want_odd, i32* parity, i32* has_t2,
                    i32* neg1, i32* neg2, i32* valid) {
    std::vector<PartsView> parts;
    parts.reserve((size_t)n);
    for (i32 i = 0; i < n; i++)
        parts.push_back(parts_from_wire(blob, offs, kinds, i));
    prep_lanes_impl(parts, fields, want_odd, parity, has_t2, neg1, neg2,
                    valid);
}

// ---------------------------------------------------------------------------
// Native interpreter surface: tx handles, deferral sessions, verify_input.
// Twin of core/interpreter.verify_script + models/batch.py
// DeferringSignatureChecker; see native/eval.hpp.

void* nat_session_new() { return new Session(); }

void nat_session_free(void* s) { delete static_cast<Session*>(s); }

void nat_session_add_known(void* s, i32 kind, i32 parity, const u8* p0, i64 l0,
                           const u8* p1, i64 l1, const u8* p2, i64 l2,
                           i32 result) {
    auto* sess = static_cast<Session*>(s);
    Bytes a(p0, p0 + l0), b(p1, p1 + l1), c(p2, p2 + l2);
    sess->known[Session::key(kind, parity, a, b, c)] = result != 0;
}

i32 nat_session_records_count(void* s) {
    return (i32)static_cast<Session*>(s)->records.size();
}

// kinds/parities: n each; lens: 3n (p0, p1, p2 lengths per record).
void nat_session_records_meta(void* s, i32* kinds, i32* parities, i64* lens) {
    fill_records_meta(static_cast<Session*>(s)->records, kinds, parities, lens);
}

void nat_session_records_data(void* s, u8* blob) {
    fill_records_data(static_cast<Session*>(s)->records, blob);
}

i64 nat_session_records_bytes(void* s) {
    return records_total_bytes(static_cast<Session*>(s)->records);
}

// --- Speculative-record drain (Session::spec; same wire shape as the
// records_* trio). spec_seen persists so re-interpretations never re-emit.

i32 nat_session_spec_count(void* s) {
    return (i32)static_cast<Session*>(s)->spec.size();
}

void nat_session_spec_meta(void* s, i32* kinds, i32* parities, i64* lens) {
    fill_records_meta(static_cast<Session*>(s)->spec, kinds, parities, lens);
}

i64 nat_session_spec_bytes(void* s) {
    return records_total_bytes(static_cast<Session*>(s)->spec);
}

void nat_session_spec_data(void* s, u8* blob) {
    auto* sess = static_cast<Session*>(s);
    fill_records_data(sess->spec, blob);
    sess->spec.clear();  // drained; spec_seen persists across rounds
}

// Batched oracle publish: check i's parts are blob[offs[3i]..offs[3i+1]) etc.
// (Record part order: ecdsa pubkey|sig|msg, schnorr pk32|sig64|msg,
// tweak q32|internal32|tweak32); kinds[i]&0xff is the kind, bit 8 the
// tweak parity; results[i] the verdict.
void nat_session_add_known_batch(void* s, i32 n, const i32* kinds,
                                 const u8* blob, const i64* offs,
                                 const i32* results) {
    auto* sess = static_cast<Session*>(s);
    for (i32 i = 0; i < n; i++) {
        const u8* p0 = blob + offs[3 * i];
        const u8* p1 = blob + offs[3 * i + 1];
        const u8* p2 = blob + offs[3 * i + 2];
        Bytes a(p0, p1), b(p1, p2), c(p2, blob + offs[3 * i + 3]);
        sess->known[Session::key(kinds[i] & 0xff, (kinds[i] >> 8) & 1, a, b,
                                 c)] = results[i] != 0;
    }
}

// Batched salted cache-key digests, byte-identical to the Python
// models/sigcache.py `_key(_parts(kind, data))` stream:
//   sha256(salt || [len(part) as 4-byte LE || part]...)
// with parts = [kind-name, data...] and the tweak parity serialized as an
// 8-byte signed little-endian int between q32 and internal32.
// Digest core shared by the wire and session-resident entries.
void digest_one(const u8* salt, i64 salt_len, const PartsView& pv, u8* out32) {
    static const char* NAMES[3] = {"ecdsa", "schnorr", "tweak"};
    Sha256 h;
    h.write(salt, (size_t)salt_len);
    if (pv.kind > KIND_TWEAK) {
        // An unsynchronized kind table must fail loudly, not read OOB.
        std::fprintf(stderr, "digest_one: bad kind %d\n", pv.kind);
        std::abort();
    }
    auto part = [&h](const u8* p, size_t len) {
        u8 lb[4] = {u8(len), u8(len >> 8), u8(len >> 16), u8(len >> 24)};
        h.write(lb, 4);
        h.write(p, len);
    };
    const char* name = NAMES[pv.kind];
    part(reinterpret_cast<const u8*>(name), std::strlen(name));
    part(pv.p0, (size_t)pv.l0);
    if (pv.kind == KIND_TWEAK) {
        u8 pb[8] = {u8(pv.parity & 1), 0, 0, 0, 0, 0, 0, 0};
        part(pb, 8);
    }
    part(pv.p1, (size_t)pv.l1);
    part(pv.p2, (size_t)pv.l2);
    h.finalize(out32);
}

void nat_digest_checks(const u8* salt, i64 salt_len, i32 n, const i32* kinds,
                       const u8* blob, const i64* offs, u8* out) {
    for (i32 i = 0; i < n; i++)
        digest_one(salt, salt_len, parts_from_wire(blob, offs, kinds, i),
                   out + 32 * (size_t)i);
}

// Generic batched salted digests over variable part lists (the script-
// execution-cache keys): item i hashes parts part_bounds[i]..part_bounds[i+1)
// with the models/sigcache.py `_key` stream layout
// (sha256(salt || [len(part) as 4-byte LE || part]...)); part j's bytes are
// blob[part_offs[j]..part_offs[j+1]).
void nat_digest_streams(const u8* salt, i64 salt_len, i32 n,
                        const i64* part_bounds, const i64* part_offs,
                        const u8* blob, u8* out) {
    for (i32 i = 0; i < n; i++) {
        Sha256 h;
        h.write(salt, (size_t)salt_len);
        for (i64 j = part_bounds[i]; j < part_bounds[i + 1]; j++) {
            size_t len = (size_t)(part_offs[j + 1] - part_offs[j]);
            u8 lb[4] = {u8(len), u8(len >> 8), u8(len >> 16), u8(len >> 24)};
            h.write(lb, 4);
            h.write(blob + part_offs[j], len);
        }
        h.finalize(out + 32 * (size_t)i);
    }
}

void* nat_tx_parse(const u8* data, i64 len) {
    try {
        return tx_parse(data, (size_t)len);
    } catch (...) {  // SerErr, bad_alloc, ... — never cross the C ABI
        return nullptr;
    }
}

void nat_tx_wtxid(void* txp, u8* out32) {
    auto* tx = static_cast<NTx*>(txp);
    Bytes b = tx->serialize(true);
    sha256d(b.data(), b.size(), out32);
}

void nat_tx_free(void* tx) { delete static_cast<NTx*>(tx); }

// Serialization export (fuzz harness + consumers needing the canonical
// bytes): two-call pattern — size, then fill.
i64 nat_tx_serialize_size(void* txp, i32 witness) {
    return (i64)static_cast<NTx*>(txp)->serialize(witness != 0).size();
}

void nat_tx_serialize(void* txp, i32 witness, u8* out) {
    Bytes b = static_cast<NTx*>(txp)->serialize(witness != 0);
    std::memcpy(out, b.data(), b.size());
}

i64 nat_tx_ser_size(void* tx) { return static_cast<NTx*>(tx)->ser_size; }

i32 nat_tx_n_inputs(void* tx) {
    return (i32)static_cast<NTx*>(tx)->vin.size();
}

// Precompute the tx-wide hash aggregates; spent outputs (one per input)
// unlock BIP341. spk_offs has n+1 entries into spk_blob.
void nat_tx_set_spent_outputs(void* txp, const i64* amounts, const u8* spk_blob,
                              const i64* spk_offs, i32 n) {
    auto* tx = static_cast<NTx*>(txp);
    std::vector<NTxOut> spent((size_t)n);
    for (i32 i = 0; i < n; i++) {
        spent[i].value = amounts[i];
        spent[i].spk.assign(spk_blob + spk_offs[i], spk_blob + spk_offs[i + 1]);
    }
    precompute(*tx, &spent);
}

void nat_tx_precompute(void* txp) {
    precompute(*static_cast<NTx*>(txp), nullptr);
}

// Verify one input. mode 0 = deferring (records + oracle via sess),
// mode 1 = exact (native curve math; sess may be NULL).
// Returns 1 ok / 0 script-failed; *script_err gets the ScriptError code,
// *unknown the count of oracle misses (deferring mode).
i32 nat_verify_input(void* s, void* txp, i32 n_in, i64 amount, const u8* spk,
                     i64 spk_len, i32 flags, i32 mode, i32* script_err,
                     i32* unknown) {
    auto* sess = static_cast<Session*>(s);
    if (sess) {
        // Symmetric with nat_verify_inputs_idx setting it true: a session
        // that served the index protocol must not keep routing the legacy
        // records path's oracle misses into uniq/rec_idx (the records
        // drain would return 0 entries while unk > 0 and the driver would
        // publish optimistic verdicts with the misses unresolved).
        sess->index_mode = false;
        sess->records.clear();
    }
    return run_verify_input(sess, static_cast<NTx*>(txp), n_in, amount, spk,
                            spk_len, flags, mode, script_err, unknown);
}

// Batched verify: n inputs in one call (the per-call ctypes cost of the
// single-input surface dominates a 3k-input block; this removes it).
// txs[i]/n_ins[i]/amounts[i]/flags[i] per input; input i's scriptPubKey is
// spk_blob[spk_offs[i]..spk_offs[i+1]). Outputs per input: ok/err/unk, and
// rec_bounds (n+1 entries) delimiting its slice of the session's records
// (drained afterwards via the records_* trio). Speculative records
// accumulate session-wide; drain via the spec_* trio.
void nat_verify_inputs(void* s, void** txs, const i32* n_ins,
                       const i64* amounts, const u8* spk_blob,
                       const i64* spk_offs, const i32* flags, i32 mode, i32 n,
                       i32* ok, i32* err, i32* unk, i64* rec_bounds) {
    auto* sess = static_cast<Session*>(s);
    if (sess) {
        sess->index_mode = false;  // see nat_verify_input's comment
        sess->records.clear();
    }
    rec_bounds[0] = 0;
    for (i32 i = 0; i < n; i++) {
        ok[i] = run_verify_input(sess, static_cast<NTx*>(txs[i]), n_ins[i],
                                 amounts[i], spk_blob + spk_offs[i],
                                 spk_offs[i + 1] - spk_offs[i], flags[i], mode,
                                 &err[i], &unk[i]);
        rec_bounds[i + 1] = sess ? (i64)sess->records.size() : 0;
    }
}

// ---------------------------------------------------------------------------
// Index-mode batch surface: the session keeps ONE deduped check list
// (`uniq`) and every consumer — lane prep for the device kernel, salted
// cache digests, verdict publication, exact host fallback — reads it in
// place. Python sees only int32 indices; no check bytes ever cross the
// bridge twice. This is the TPU-era CCheckQueue fan-out
// (checkqueue.h:29-163): `n_threads` shards the per-input interpretation
// across worker threads that share the session's oracle read-only and
// merge their discovered checks serially (order-preserving, so lane
// order is deterministic regardless of thread count).

// Interpret inputs [lo, hi) against `sess` (which may be a worker
// scratch whose `oracle` points at the shared session). Per-input
// rec_idx bounds are recorded into local_bounds[lo..hi].
static void run_idx_range(Session* sess, void** txs, const i32* n_ins,
                          const i64* amounts, const u8* spk_blob,
                          const i64* spk_offs, const i32* flags, i32 lo,
                          i32 hi, i32* ok, i32* err, i32* unk,
                          i64* local_bounds) {
    for (i32 i = lo; i < hi; i++) {
        ok[i] = run_verify_input(sess, static_cast<NTx*>(txs[i]), n_ins[i],
                                 amounts[i], spk_blob + spk_offs[i],
                                 spk_offs[i + 1] - spk_offs[i], flags[i],
                                 MODE_DEFER, &err[i], &unk[i]);
        local_bounds[i + 1] = (i64)sess->rec_idx.size();
    }
}

void nat_verify_inputs_idx(void* s, void** txs, const i32* n_ins,
                           const i64* amounts, const u8* spk_blob,
                           const i64* spk_offs, const i32* flags, i32 n,
                           i32 n_threads, i32* ok, i32* err, i32* unk,
                           i64* rec_bounds) {
    auto* sess = static_cast<Session*>(s);
    sess->index_mode = true;
    sess->rec_idx.clear();
    rec_bounds[0] = 0;
    if (n_threads < 2 || n < 2 * n_threads) {
        // rec_idx was just cleared, so per-input bounds are global bounds.
        run_idx_range(sess, txs, n_ins, amounts, spk_blob, spk_offs, flags, 0,
                      n, ok, err, unk, rec_bounds);
        return;
    }
    i32 T = n_threads;
    std::vector<Session> scratch((size_t)T);
    std::vector<std::vector<i64>> bounds((size_t)T);
    std::vector<std::thread> workers;
    workers.reserve((size_t)T);
    for (i32 t = 0; t < T; t++) {
        scratch[t].index_mode = true;
        scratch[t].oracle = sess;
        bounds[t].assign((size_t)n + 1, 0);
        i32 lo = (i32)((i64)n * t / T);
        i32 hi = (i32)((i64)n * (t + 1) / T);
        workers.emplace_back([&, t, lo, hi] {
            // The scratch session's rec_idx is empty at entry, so the
            // worker's bounds slots [lo+1, hi] are relative to 0.
            run_idx_range(&scratch[t], txs, n_ins, amounts, spk_blob,
                          spk_offs, flags, lo, hi, ok, err, unk,
                          bounds[t].data());
        });
    }
    for (auto& w : workers) w.join();
    // Serial merge in shard order: dedup each scratch's uniq into the
    // shared session, remap its rec_idx entries, and lay down global
    // rec_bounds — identical discovery order to a single-threaded run
    // over the same shard sequence.
    for (i32 t = 0; t < T; t++) {
        Session& sc = scratch[t];
        std::vector<i32> remap(sc.uniq.size());
        for (size_t j = 0; j < sc.uniq.size(); j++) {
            auto ins = sess->uniq_seen.try_emplace(std::move(sc.uniq_keys[j]),
                                                   (i32)sess->uniq.size());
            if (ins.second) {
                sess->uniq.push_back(std::move(sc.uniq[j]));
                sess->uniq_keys.push_back(ins.first->first);
            }
            remap[j] = ins.first->second;
        }
        i32 lo = (i32)((i64)n * t / T);
        i32 hi = (i32)((i64)n * (t + 1) / T);
        for (i32 i = lo; i < hi; i++) {
            for (i64 j = bounds[t][(size_t)i]; j < bounds[t][(size_t)i + 1];
                 j++)
                sess->rec_idx.push_back(remap[(size_t)sc.rec_idx[(size_t)j]]);
            rec_bounds[i + 1] = (i64)sess->rec_idx.size();
        }
    }
}

i32 nat_session_uniq_count(void* s) {
    return (i32)static_cast<Session*>(s)->uniq.size();
}

// A stale or negative uniq index from the driver is an OOB read / heap
// corruption; fail loudly instead (same pattern as digest_one's kind
// guard).
inline const Record& uniq_at(Session* sess, i32 idx) {
    if (idx < 0 || (size_t)idx >= sess->uniq.size()) {
        std::fprintf(stderr, "uniq_at: index %d out of range (uniq size %zu)\n",
                     idx, sess->uniq.size());
        std::abort();
    }
    return sess->uniq[(size_t)idx];
}

// `capacity` is the caller's buffer size (the rec_idx length observed at
// verify time); ctypes releases the GIL during calls, so copying
// rec_idx.size() entries unchecked would overflow the buffer if another
// thread grew the session in between. Returns the count actually copied.
i64 nat_session_recidx_data(void* s, i32* out, i64 capacity) {
    auto* sess = static_cast<Session*>(s);
    i64 n = (i64)sess->rec_idx.size();
    if (capacity < n) n = capacity;
    if (n > 0) std::memcpy(out, sess->rec_idx.data(), (size_t)n * sizeof(i32));
    return n;
}

// Kernel lanes for uniq[idxs[0..nidx)] — session-resident prep, no wire
// blob. Output layout identical to nat_prep_lanes.
void nat_session_uniq_lanes(void* s, const i32* idxs, i32 nidx, u8* fields,
                            i32* want_odd, i32* parity, i32* has_t2,
                            i32* neg1, i32* neg2, i32* valid) {
    auto* sess = static_cast<Session*>(s);
    std::vector<PartsView> parts;
    parts.reserve((size_t)nidx);
    for (i32 j = 0; j < nidx; j++)
        parts.push_back(parts_from_record_lanes(uniq_at(sess, idxs[j])));
    prep_lanes_impl(parts, fields, want_odd, parity, has_t2, neg1, neg2,
                    valid);
}

// Salted cache-key digests for uniq[idxs[0..nidx)] (models/sigcache.py
// key stream — same bytes nat_digest_checks produces for the wire shape).
void nat_session_uniq_digests(void* s, const u8* salt, i64 salt_len,
                              const i32* idxs, i32 nidx, u8* out) {
    auto* sess = static_cast<Session*>(s);
    for (i32 j = 0; j < nidx; j++)
        digest_one(salt, salt_len, parts_from_record(uniq_at(sess, idxs[j])),
                   out + 32 * (size_t)j);
}

// Publish device/cache verdicts for uniq[idxs[0..nidx)] into the oracle.
void nat_session_publish_uniq(void* s, const i32* idxs, i32 nidx,
                              const i32* results) {
    auto* sess = static_cast<Session*>(s);
    for (i32 j = 0; j < nidx; j++) {
        uniq_at(sess, idxs[j]);  // bounds guard (uniq_keys is parallel)
        sess->known[sess->uniq_keys[(size_t)idxs[j]]] = results[j] != 0;
    }
}

// Exact host verdict for one uniq entry (the exceptional-lane fixup path:
// crafted scalar collisions the fast device adds defer — never honest
// traffic).
i32 nat_session_uniq_host_verify(void* s, i32 idx) {
    auto* sess = static_cast<Session*>(s);
    const Record& r = uniq_at(sess, idx);
    if (r.kind == KIND_ECDSA)
        return verify_ecdsa(r.p0.data(), r.p0.size(), r.p1.data(),
                            r.p1.size(), r.p2.data())
                   ? 1
                   : 0;
    if (r.kind == KIND_SCHNORR)
        return verify_schnorr(r.p0.data(), r.p1.data(), r.p2.data()) ? 1 : 0;
    // tweak record order: q32 | internal32 | tweak32
    return tweak_add_check(r.p0.data(), r.parity, r.p1.data(), r.p2.data())
               ? 1
               : 0;
}

}  // extern "C"
