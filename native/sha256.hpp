// SHA-256 for the native host core: streaming, midstate resume, sha256d,
// and BIP340 tagged hashing. Spec: FIPS 180-4 (constants are the published
// spec values, identical in every implementation). Reference parity:
// crypto/sha256.cpp (generic transform) + hash.cpp:89-96 TaggedHash +
// modules/schnorrsig/main_impl.h:96-109 (hardcoded tag midstates) — the
// midstate-resume API here serves the same amortization.
// A SHA-NI (x86 SHA extensions) transform is selected at runtime when the
// CPU supports it — same output, ~5x the scalar transform's throughput;
// the reference gates the equivalent specializations the same way
// (crypto/sha256.cpp SelfTest + cpuid dispatch).
#pragma once

#include <cstdint>
#include <cstring>

// __builtin_cpu_supports("sha") is only a valid feature string from
// GCC 11 (clang has carried it longer); older GCC rejects it at compile
// time, so the whole SHA-NI path is gated out there and the scalar
// transform below serves every call.
#if defined(__x86_64__) && defined(__GNUC__) && \
    (defined(__clang__) || __GNUC__ >= 11)
#define NAT_SHA_NI_POSSIBLE 1
#include <immintrin.h>
#endif

namespace nat {

using u8 = uint8_t;
using u32 = uint32_t;
using u64 = uint64_t;

#ifdef NAT_SHA_NI_POSSIBLE
// One-block compression via the SHA-NI instructions. State layout note:
// the SHA-NI registers hold (ABEF, CDGH); the wrappers below shuffle to
// and from the linear a..h word order.
__attribute__((target("sha,sse4.1"))) inline void sha_ni_transform(
    u32 s[8], const u8* p) {
    __m128i STATE0, STATE1, MSG, TMP, MSG0, MSG1, MSG2, MSG3;
    const __m128i MASK =
        _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);

    TMP = _mm_loadu_si128(reinterpret_cast<const __m128i*>(&s[0]));
    STATE1 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(&s[4]));
    TMP = _mm_shuffle_epi32(TMP, 0xB1);        // CDAB
    STATE1 = _mm_shuffle_epi32(STATE1, 0x1B);  // EFGH
    STATE0 = _mm_alignr_epi8(TMP, STATE1, 8);  // ABEF
    STATE1 = _mm_blend_epi16(STATE1, TMP, 0xF0);  // CDGH

    const __m128i ABEF_SAVE = STATE0;
    const __m128i CDGH_SAVE = STATE1;

#define NAT_SHA_RND(M, K0, K1)                                        \
    MSG = _mm_add_epi32(M, _mm_set_epi64x((long long)(K1), (long long)(K0))); \
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);              \
    MSG = _mm_shuffle_epi32(MSG, 0x0E);                               \
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG)

    MSG0 = _mm_shuffle_epi8(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(p + 0)), MASK);
    NAT_SHA_RND(MSG0, 0x71374491428a2f98ULL, 0xe9b5dba5b5c0fbcfULL);
    MSG1 = _mm_shuffle_epi8(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(p + 16)), MASK);
    NAT_SHA_RND(MSG1, 0x59f111f13956c25bULL, 0xab1c5ed5923f82a4ULL);
    MSG0 = _mm_sha256msg1_epu32(MSG0, MSG1);
    MSG2 = _mm_shuffle_epi8(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(p + 32)), MASK);
    NAT_SHA_RND(MSG2, 0x12835b01d807aa98ULL, 0x550c7dc3243185beULL);
    MSG1 = _mm_sha256msg1_epu32(MSG1, MSG2);
    MSG3 = _mm_shuffle_epi8(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(p + 48)), MASK);
    NAT_SHA_RND(MSG3, 0x80deb1fe72be5d74ULL, 0xc19bf1749bdc06a7ULL);

    for (int i = 0; i < 3; i++) {
        static const u64 KS[3][8] = {
            {0xefbe4786e49b69c1ULL, 0x240ca1cc0fc19dc6ULL,
             0x4a7484aa2de92c6fULL, 0x76f988da5cb0a9dcULL,
             0xa831c66d983e5152ULL, 0xbf597fc7b00327c8ULL,
             0xd5a79147c6e00bf3ULL, 0x1429296706ca6351ULL},
            {0x2e1b213827b70a85ULL, 0x53380d134d2c6dfcULL,
             0x766a0abb650a7354ULL, 0x92722c8581c2c92eULL,
             0xa81a664ba2bfe8a1ULL, 0xc76c51a3c24b8b70ULL,
             0xd6990624d192e819ULL, 0x106aa070f40e3585ULL},
            {0x1e376c0819a4c116ULL, 0x34b0bcb52748774cULL,
             0x4ed8aa4a391c0cb3ULL, 0x682e6ff35b9cca4fULL,
             0x78a5636f748f82eeULL, 0x8cc7020884c87814ULL,
             0xa4506ceb90befffaULL, 0xc67178f2bef9a3f7ULL},
        };
        const u64* K = KS[i];
        MSG0 = _mm_sha256msg2_epu32(
            _mm_add_epi32(MSG0, _mm_alignr_epi8(MSG3, MSG2, 4)), MSG3);
        MSG2 = _mm_sha256msg1_epu32(MSG2, MSG3);
        MSG = _mm_add_epi32(MSG0, _mm_set_epi64x((long long)K[1], (long long)K[0]));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
        MSG1 = _mm_sha256msg2_epu32(
            _mm_add_epi32(MSG1, _mm_alignr_epi8(MSG0, MSG3, 4)), MSG0);
        MSG3 = _mm_sha256msg1_epu32(MSG3, MSG0);
        MSG = _mm_add_epi32(MSG1, _mm_set_epi64x((long long)K[3], (long long)K[2]));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
        MSG2 = _mm_sha256msg2_epu32(
            _mm_add_epi32(MSG2, _mm_alignr_epi8(MSG1, MSG0, 4)), MSG1);
        MSG0 = _mm_sha256msg1_epu32(MSG0, MSG1);
        MSG = _mm_add_epi32(MSG2, _mm_set_epi64x((long long)K[5], (long long)K[4]));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
        MSG3 = _mm_sha256msg2_epu32(
            _mm_add_epi32(MSG3, _mm_alignr_epi8(MSG2, MSG1, 4)), MSG2);
        MSG1 = _mm_sha256msg1_epu32(MSG1, MSG2);
        MSG = _mm_add_epi32(MSG3, _mm_set_epi64x((long long)K[7], (long long)K[6]));
        STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
        MSG = _mm_shuffle_epi32(MSG, 0x0E);
        STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    }
#undef NAT_SHA_RND

    STATE0 = _mm_add_epi32(STATE0, ABEF_SAVE);
    STATE1 = _mm_add_epi32(STATE1, CDGH_SAVE);
    TMP = _mm_shuffle_epi32(STATE0, 0x1B);        // FEBA
    STATE1 = _mm_shuffle_epi32(STATE1, 0xB1);     // DCHG
    STATE0 = _mm_blend_epi16(TMP, STATE1, 0xF0);  // DCBA
    STATE1 = _mm_alignr_epi8(STATE1, TMP, 8);     // HGFE
    _mm_storeu_si128(reinterpret_cast<__m128i*>(&s[0]), STATE0);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(&s[4]), STATE1);
}

inline bool sha_ni_available() {
    static const bool ok = __builtin_cpu_supports("sha") &&
                           __builtin_cpu_supports("sse4.1");
    return ok;
}
#endif  // NAT_SHA_NI_POSSIBLE

struct Sha256 {
    u32 s[8];
    u8 buf[64];
    u64 bytes;

    Sha256() { reset(); }

    void reset() {
        static const u32 init[8] = {0x6a09e667u, 0xbb67ae85u, 0x3c6ef372u,
                                    0xa54ff53au, 0x510e527fu, 0x9b05688cu,
                                    0x1f83d9abu, 0x5be0cd19u};
        std::memcpy(s, init, sizeof(s));
        bytes = 0;
    }

    // Resume from a known 8-word state that already absorbed `absorbed`
    // bytes (a multiple of 64) — the tagged-hash midstate trick.
    void resume(const u32 state[8], u64 absorbed) {
        std::memcpy(s, state, sizeof(s));
        bytes = absorbed;
    }

    static inline u32 rotr(u32 x, int n) { return (x >> n) | (x << (32 - n)); }

    void transform(const u8* p) {
#ifdef NAT_SHA_NI_POSSIBLE
        if (sha_ni_available()) {
            sha_ni_transform(s, p);
            return;
        }
#endif
        transform_scalar(p);
    }

    void transform_scalar(const u8* p) {
        static const u32 K[64] = {
            0x428a2f98u, 0x71374491u, 0xb5c0fbcfu, 0xe9b5dba5u, 0x3956c25bu,
            0x59f111f1u, 0x923f82a4u, 0xab1c5ed5u, 0xd807aa98u, 0x12835b01u,
            0x243185beu, 0x550c7dc3u, 0x72be5d74u, 0x80deb1feu, 0x9bdc06a7u,
            0xc19bf174u, 0xe49b69c1u, 0xefbe4786u, 0x0fc19dc6u, 0x240ca1ccu,
            0x2de92c6fu, 0x4a7484aau, 0x5cb0a9dcu, 0x76f988dau, 0x983e5152u,
            0xa831c66du, 0xb00327c8u, 0xbf597fc7u, 0xc6e00bf3u, 0xd5a79147u,
            0x06ca6351u, 0x14292967u, 0x27b70a85u, 0x2e1b2138u, 0x4d2c6dfcu,
            0x53380d13u, 0x650a7354u, 0x766a0abbu, 0x81c2c92eu, 0x92722c85u,
            0xa2bfe8a1u, 0xa81a664bu, 0xc24b8b70u, 0xc76c51a3u, 0xd192e819u,
            0xd6990624u, 0xf40e3585u, 0x106aa070u, 0x19a4c116u, 0x1e376c08u,
            0x2748774cu, 0x34b0bcb5u, 0x391c0cb3u, 0x4ed8aa4au, 0x5b9cca4fu,
            0x682e6ff3u, 0x748f82eeu, 0x78a5636fu, 0x84c87814u, 0x8cc70208u,
            0x90befffau, 0xa4506cebu, 0xbef9a3f7u, 0xc67178f2u};
        u32 w[64];
        for (int i = 0; i < 16; i++)
            w[i] = (u32(p[4 * i]) << 24) | (u32(p[4 * i + 1]) << 16) |
                   (u32(p[4 * i + 2]) << 8) | u32(p[4 * i + 3]);
        for (int i = 16; i < 64; i++) {
            u32 s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
            u32 s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        u32 a = s[0], b = s[1], c = s[2], d = s[3];
        u32 e = s[4], f = s[5], g = s[6], h = s[7];
        for (int i = 0; i < 64; i++) {
            u32 S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
            u32 ch = (e & f) ^ (~e & g);
            u32 t1 = h + S1 + ch + K[i] + w[i];
            u32 S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
            u32 maj = (a & b) ^ (a & c) ^ (b & c);
            u32 t2 = S0 + maj;
            h = g; g = f; f = e; e = d + t1;
            d = c; c = b; b = a; a = t1 + t2;
        }
        s[0] += a; s[1] += b; s[2] += c; s[3] += d;
        s[4] += e; s[5] += f; s[6] += g; s[7] += h;
    }

    Sha256& write(const u8* data, size_t len) {
        size_t fill = bytes % 64;
        bytes += len;
        if (fill) {
            size_t take = 64 - fill;
            if (take > len) take = len;
            std::memcpy(buf + fill, data, take);
            data += take;
            len -= take;
            if (fill + take == 64) transform(buf);
            else return *this;
        }
        while (len >= 64) {
            transform(data);
            data += 64;
            len -= 64;
        }
        if (len) std::memcpy(buf, data, len);
        return *this;
    }

    void finalize(u8 out[32]) {
        u64 msgbits = bytes * 8;
        u8 pad = 0x80;
        write(&pad, 1);
        u8 zero = 0;
        while (bytes % 64 != 56) write(&zero, 1);
        u8 lenb[8];
        for (int i = 0; i < 8; i++) lenb[i] = u8(msgbits >> (56 - 8 * i));
        write(lenb, 8);
        for (int i = 0; i < 8; i++) {
            out[4 * i] = u8(s[i] >> 24);
            out[4 * i + 1] = u8(s[i] >> 16);
            out[4 * i + 2] = u8(s[i] >> 8);
            out[4 * i + 3] = u8(s[i]);
        }
    }
};

inline void sha256(const u8* data, size_t len, u8 out[32]) {
    Sha256 h;
    h.write(data, len);
    h.finalize(out);
}

inline void sha256d(const u8* data, size_t len, u8 out[32]) {
    u8 tmp[32];
    sha256(data, len, tmp);
    sha256(tmp, 32, out);
}

// Midstate after absorbing sha256(tag)||sha256(tag) — one 64-byte block.
struct TagMidstate {
    u32 s[8];

    explicit TagMidstate(const char* tag) {
        u8 th[32];
        sha256(reinterpret_cast<const u8*>(tag), std::strlen(tag), th);
        Sha256 h;
        h.write(th, 32);
        h.write(th, 32);
        // exactly one block absorbed; state is the midstate
        std::memcpy(s, h.s, sizeof(s));
    }

    void hash(const u8* data, size_t len, u8 out[32]) const {
        Sha256 h;
        h.resume(s, 64);
        h.write(data, len);
        h.finalize(out);
    }
};

}  // namespace nat
