// RIPEMD-160 and SHA-1 for the native script machine (OP_RIPEMD160,
// OP_SHA1, OP_HASH160). Published-spec constants; reference parity:
// crypto/ripemd160.cpp, crypto/sha1.cpp (generic transforms).
#pragma once

#include <cstdint>
#include <cstring>

#include "sha256.hpp"

namespace nat {

// ---------------------------------------------------------------------------
// RIPEMD-160

struct Ripemd160 {
    static inline u32 rol(u32 x, int n) { return (x << n) | (x >> (32 - n)); }

    static void hash(const u8* data, size_t len, u8 out[20]) {
        u32 h0 = 0x67452301u, h1 = 0xEFCDAB89u, h2 = 0x98BADCFEu,
            h3 = 0x10325476u, h4 = 0xC3D2E1F0u;
        // message with padding
        u64 msgbits = (u64)len * 8;
        size_t padlen = ((len + 8) / 64 + 1) * 64;
        // process in chunks without allocating when possible
        u8 tail[128];
        size_t full = len / 64 * 64;

        auto compress = [&](const u8* p) {
            static const int R1[80] = {
                0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15,
                7, 4, 13, 1, 10, 6, 15, 3, 12, 0, 9, 5, 2, 14, 11, 8,
                3, 10, 14, 4, 9, 15, 8, 1, 2, 7, 0, 6, 13, 11, 5, 12,
                1, 9, 11, 10, 0, 8, 12, 4, 13, 3, 7, 15, 14, 5, 6, 2,
                4, 0, 5, 9, 7, 12, 2, 10, 14, 1, 3, 8, 11, 6, 15, 13};
            static const int R2[80] = {
                5, 14, 7, 0, 9, 2, 11, 4, 13, 6, 15, 8, 1, 10, 3, 12,
                6, 11, 3, 7, 0, 13, 5, 10, 14, 15, 8, 12, 4, 9, 1, 2,
                15, 5, 1, 3, 7, 14, 6, 9, 11, 8, 12, 2, 10, 0, 4, 13,
                8, 6, 4, 1, 3, 11, 15, 0, 5, 12, 2, 13, 9, 7, 10, 14,
                12, 15, 10, 4, 1, 5, 8, 7, 6, 2, 13, 14, 0, 3, 9, 11};
            static const int S1[80] = {
                11, 14, 15, 12, 5, 8, 7, 9, 11, 13, 14, 15, 6, 7, 9, 8,
                7, 6, 8, 13, 11, 9, 7, 15, 7, 12, 15, 9, 11, 7, 13, 12,
                11, 13, 6, 7, 14, 9, 13, 15, 14, 8, 13, 6, 5, 12, 7, 5,
                11, 12, 14, 15, 14, 15, 9, 8, 9, 14, 5, 6, 8, 6, 5, 12,
                9, 15, 5, 11, 6, 8, 13, 12, 5, 12, 13, 14, 11, 8, 5, 6};
            static const int S2[80] = {
                8, 9, 9, 11, 13, 15, 15, 5, 7, 7, 8, 11, 14, 14, 12, 6,
                9, 13, 15, 7, 12, 8, 9, 11, 7, 7, 12, 7, 6, 15, 13, 11,
                9, 7, 15, 11, 8, 6, 6, 14, 12, 13, 5, 14, 13, 13, 7, 5,
                15, 5, 8, 11, 14, 14, 6, 14, 6, 9, 12, 9, 12, 5, 15, 8,
                8, 5, 12, 9, 12, 5, 14, 6, 8, 13, 6, 5, 15, 13, 11, 11};
            static const u32 K1[5] = {0x00000000u, 0x5A827999u, 0x6ED9EBA1u,
                                      0x8F1BBCDCu, 0xA953FD4Eu};
            static const u32 K2[5] = {0x50A28BE6u, 0x5C4DD124u, 0x6D703EF3u,
                                      0x7A6D76E9u, 0x00000000u};
            u32 x[16];
            for (int i = 0; i < 16; i++)
                x[i] = (u32)p[4 * i] | ((u32)p[4 * i + 1] << 8) |
                       ((u32)p[4 * i + 2] << 16) | ((u32)p[4 * i + 3] << 24);
            u32 a1 = h0, b1 = h1, c1 = h2, d1 = h3, e1 = h4;
            u32 a2 = h0, b2 = h1, c2 = h2, d2 = h3, e2 = h4;
            for (int j = 0; j < 80; j++) {
                int rnd = j / 16;
                u32 f1, f2;
                switch (rnd) {
                    case 0: f1 = b1 ^ c1 ^ d1; f2 = b2 ^ (c2 | ~d2); break;
                    case 1: f1 = (b1 & c1) | (~b1 & d1); f2 = (b2 & d2) | (c2 & ~d2); break;
                    case 2: f1 = (b1 | ~c1) ^ d1; f2 = (b2 | ~c2) ^ d2; break;
                    case 3: f1 = (b1 & d1) | (c1 & ~d1); f2 = (b2 & c2) | (~b2 & d2); break;
                    default: f1 = b1 ^ (c1 | ~d1); f2 = b2 ^ c2 ^ d2; break;
                }
                u32 t = rol(a1 + f1 + x[R1[j]] + K1[rnd], S1[j]) + e1;
                a1 = e1; e1 = d1; d1 = rol(c1, 10); c1 = b1; b1 = t;
                t = rol(a2 + f2 + x[R2[j]] + K2[rnd], S2[j]) + e2;
                a2 = e2; e2 = d2; d2 = rol(c2, 10); c2 = b2; b2 = t;
            }
            u32 t = h1 + c1 + d2;
            h1 = h2 + d1 + e2;
            h2 = h3 + e1 + a2;
            h3 = h4 + a1 + b2;
            h4 = h0 + b1 + c2;
            h0 = t;
        };

        for (size_t off = 0; off < full; off += 64) compress(data + off);
        size_t rem = len - full;
        if (rem) std::memcpy(tail, data + full, rem);
        tail[rem] = 0x80;
        size_t tail_len = (rem + 8 < 64) ? 64 : 128;
        std::memset(tail + rem + 1, 0, tail_len - rem - 1 - 8);
        for (int i = 0; i < 8; i++) tail[tail_len - 8 + i] = u8(msgbits >> (8 * i));
        compress(tail);
        if (tail_len == 128) compress(tail + 64);
        (void)padlen;
        u32 hs[5] = {h0, h1, h2, h3, h4};
        for (int i = 0; i < 5; i++)
            for (int j = 0; j < 4; j++) out[4 * i + j] = u8(hs[i] >> (8 * j));
    }
};

inline void ripemd160(const u8* data, size_t len, u8 out[20]) {
    Ripemd160::hash(data, len, out);
}

inline void hash160(const u8* data, size_t len, u8 out[20]) {
    u8 tmp[32];
    sha256(data, len, tmp);
    ripemd160(tmp, 32, out);
}

// ---------------------------------------------------------------------------
// SHA-1

inline void sha1(const u8* data, size_t len, u8 out[20]) {
    u32 h[5] = {0x67452301u, 0xEFCDAB89u, 0x98BADCFEu, 0x10325476u, 0xC3D2E1F0u};
    auto rol = [](u32 x, int n) { return (x << n) | (x >> (32 - n)); };
    auto compress = [&](const u8* p) {
        u32 w[80];
        for (int i = 0; i < 16; i++)
            w[i] = ((u32)p[4 * i] << 24) | ((u32)p[4 * i + 1] << 16) |
                   ((u32)p[4 * i + 2] << 8) | (u32)p[4 * i + 3];
        for (int i = 16; i < 80; i++)
            w[i] = rol(w[i - 3] ^ w[i - 8] ^ w[i - 14] ^ w[i - 16], 1);
        u32 a = h[0], b = h[1], c = h[2], d = h[3], e = h[4];
        for (int i = 0; i < 80; i++) {
            u32 f, k;
            if (i < 20) { f = (b & c) | (~b & d); k = 0x5A827999u; }
            else if (i < 40) { f = b ^ c ^ d; k = 0x6ED9EBA1u; }
            else if (i < 60) { f = (b & c) | (b & d) | (c & d); k = 0x8F1BBCDCu; }
            else { f = b ^ c ^ d; k = 0xCA62C1D6u; }
            u32 t = rol(a, 5) + f + e + k + w[i];
            e = d; d = c; c = rol(b, 30); b = a; a = t;
        }
        h[0] += a; h[1] += b; h[2] += c; h[3] += d; h[4] += e;
    };
    size_t full = len / 64 * 64;
    for (size_t off = 0; off < full; off += 64) compress(data + off);
    u8 tail[128];
    size_t rem = len - full;
    if (rem) std::memcpy(tail, data + full, rem);
    tail[rem] = 0x80;
    size_t tail_len = (rem + 8 < 64) ? 64 : 128;
    std::memset(tail + rem + 1, 0, tail_len - rem - 1 - 8);
    u64 msgbits = (u64)len * 8;
    for (int i = 0; i < 8; i++) tail[tail_len - 8 + i] = u8(msgbits >> (56 - 8 * i));
    compress(tail);
    if (tail_len == 128) compress(tail + 64);
    for (int i = 0; i < 5; i++)
        for (int j = 0; j < 4; j++) out[4 * i + j] = u8(h[i] >> (24 - 8 * j));
}

// MurmurHash3 x86_32 (hash.cpp:16-78 — compiled crate surface, used by
// Core's bloom filters; unused by the verify path but part of drop-in
// completeness). Standard smhasher algorithm; values asserted against the
// reference implementation's outputs in tests/test_core_basics.py.
inline u32 murmur3_32(u32 seed, const u8* data, size_t len) {
    u32 h1 = seed;
    const u32 c1 = 0xcc9e2d51u, c2 = 0x1b873593u;
    auto rotl = [](u32 x, int r) { return (x << r) | (x >> (32 - r)); };
    size_t nblocks = len / 4;
    for (size_t i = 0; i < nblocks; i++) {
        const u8* p = data + i * 4;
        u32 k1 = (u32)p[0] | ((u32)p[1] << 8) | ((u32)p[2] << 16) |
                 ((u32)p[3] << 24);
        k1 *= c1;
        k1 = rotl(k1, 15);
        k1 *= c2;
        h1 ^= k1;
        h1 = rotl(h1, 13);
        h1 = h1 * 5 + 0xe6546b64u;
    }
    const u8* tail = data + nblocks * 4;
    u32 k1 = 0;
    switch (len & 3) {
        case 3: k1 ^= (u32)tail[2] << 16; [[fallthrough]];
        case 2: k1 ^= (u32)tail[1] << 8; [[fallthrough]];
        case 1:
            k1 ^= tail[0];
            k1 *= c1;
            k1 = rotl(k1, 15);
            k1 *= c2;
            h1 ^= k1;
    }
    h1 ^= (u32)len;
    h1 ^= h1 >> 16;
    h1 *= 0x85ebca6bu;
    h1 ^= h1 >> 13;
    h1 *= 0xc2b2ae35u;
    h1 ^= h1 >> 16;
    return h1;
}

}  // namespace nat
