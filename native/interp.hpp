// Native host consensus core: transaction codec, signature hashes
// (legacy / BIP143 / BIP341) and the full script interpreter with the
// deferred-signature seam.
//
// This is the C++ twin of the Python engine in
// `bitcoinconsensus_tpu/core/{tx,serialize,script,sighash,interpreter}.py`
// — same rules, same ScriptError codes (core/script_error.py numbering),
// same deferral protocol (models/batch.py DeferringSignatureChecker).
// The Python engine remains the executable spec; tests/test_native_interp.py
// asserts byte-for-byte agreement across the consensus vectors and random
// scripts. Reference anchors for the rules themselves:
// script/interpreter.cpp:431-1259 (EvalScript), :1937-2056 (VerifyScript),
// :1273-1364/:1577-1642 (legacy sighash), :1581-1625 (BIP143),
// :1491-1574 (BIP341), primitives/transaction.h:187-253 (codec),
// script/script.h:218-391 (CScriptNum).
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <set>
#include <stdexcept>
#include <string>
#include <unordered_map>
#include <vector>

#include "hash_extra.hpp"
#include "secp.hpp"
#include "sha256.hpp"

namespace nat {

using Bytes = std::vector<u8>;

// --------------------------------------------------------------------------
// Script error codes: EXACT mirror of core/script_error.py (IntEnum order).
enum ScriptErr : i32 {
    SE_OK = 0,
    SE_UNKNOWN_ERROR,
    SE_EVAL_FALSE,
    SE_OP_RETURN,
    SE_SCRIPT_SIZE,
    SE_PUSH_SIZE,
    SE_OP_COUNT,
    SE_STACK_SIZE,
    SE_SIG_COUNT,
    SE_PUBKEY_COUNT,
    SE_VERIFY,
    SE_EQUALVERIFY,
    SE_CHECKMULTISIGVERIFY,
    SE_CHECKSIGVERIFY,
    SE_NUMEQUALVERIFY,
    SE_BAD_OPCODE,
    SE_DISABLED_OPCODE,
    SE_INVALID_STACK_OPERATION,
    SE_INVALID_ALTSTACK_OPERATION,
    SE_UNBALANCED_CONDITIONAL,
    SE_NEGATIVE_LOCKTIME,
    SE_UNSATISFIED_LOCKTIME,
    SE_SIG_HASHTYPE,
    SE_SIG_DER,
    SE_MINIMALDATA,
    SE_SIG_PUSHONLY,
    SE_SIG_HIGH_S,
    SE_SIG_NULLDUMMY,
    SE_PUBKEYTYPE,
    SE_CLEANSTACK,
    SE_MINIMALIF,
    SE_SIG_NULLFAIL,
    SE_DISCOURAGE_UPGRADABLE_NOPS,
    SE_DISCOURAGE_UPGRADABLE_WITNESS_PROGRAM,
    SE_DISCOURAGE_UPGRADABLE_TAPROOT_VERSION,
    SE_DISCOURAGE_OP_SUCCESS,
    SE_DISCOURAGE_UPGRADABLE_PUBKEYTYPE,
    SE_WITNESS_PROGRAM_WRONG_LENGTH,
    SE_WITNESS_PROGRAM_WITNESS_EMPTY,
    SE_WITNESS_PROGRAM_MISMATCH,
    SE_WITNESS_MALLEATED,
    SE_WITNESS_MALLEATED_P2SH,
    SE_WITNESS_UNEXPECTED,
    SE_WITNESS_PUBKEYTYPE,
    SE_SCHNORR_SIG_SIZE,
    SE_SCHNORR_SIG_HASHTYPE,
    SE_SCHNORR_SIG,
    SE_TAPROOT_WRONG_CONTROL_SIZE,
    SE_TAPSCRIPT_VALIDATION_WEIGHT,
    SE_TAPSCRIPT_CHECKMULTISIG,
    SE_TAPSCRIPT_MINIMALIF,
    SE_OP_CODESEPARATOR,
    SE_SIG_FINDANDDELETE,
};

// Verification flag bits: mirror of core/flags.py / interpreter.h:41-142.
enum : u32 {
    F_P2SH = 1u << 0,
    F_STRICTENC = 1u << 1,
    F_DERSIG = 1u << 2,
    F_LOW_S = 1u << 3,
    F_NULLDUMMY = 1u << 4,
    F_SIGPUSHONLY = 1u << 5,
    F_MINIMALDATA = 1u << 6,
    F_DISCOURAGE_UPGRADABLE_NOPS = 1u << 7,
    F_CLEANSTACK = 1u << 8,
    F_CLTV = 1u << 9,
    F_CSV = 1u << 10,
    F_WITNESS = 1u << 11,
    F_DISCOURAGE_UPGRADABLE_WITNESS_PROGRAM = 1u << 12,
    F_MINIMALIF = 1u << 13,
    F_NULLFAIL = 1u << 14,
    F_WITNESS_PUBKEYTYPE = 1u << 15,
    F_CONST_SCRIPTCODE = 1u << 16,
    F_TAPROOT = 1u << 17,
    F_DISCOURAGE_UPGRADABLE_TAPROOT_VERSION = 1u << 18,
    F_DISCOURAGE_OP_SUCCESS = 1u << 19,
    F_DISCOURAGE_UPGRADABLE_PUBKEYTYPE = 1u << 20,
};

// Consensus limits (script.h:23-56).
constexpr size_t MAX_SCRIPT_ELEMENT_SIZE = 520;
constexpr int MAX_OPS_PER_SCRIPT = 201;
constexpr int MAX_PUBKEYS_PER_MULTISIG = 20;
constexpr size_t MAX_SCRIPT_SIZE = 10000;
constexpr size_t MAX_STACK_SIZE = 1000;
constexpr i64 LOCKTIME_THRESHOLD = 500000000;
constexpr u8 ANNEX_TAG = 0x50;
constexpr i64 VALIDATION_WEIGHT_PER_SIGOP_PASSED = 50;
constexpr i64 VALIDATION_WEIGHT_OFFSET = 50;
constexpr u64 SER_MAX_SIZE = 0x02000000;  // serialize.h MAX_SIZE

// Opcodes used by name below.
enum : int {
    OP_0 = 0x00, OP_PUSHDATA1 = 0x4C, OP_PUSHDATA2 = 0x4D, OP_PUSHDATA4 = 0x4E,
    OP_1NEGATE = 0x4F, OP_RESERVED = 0x50, OP_1 = 0x51, OP_16 = 0x60,
    OP_NOP = 0x61, OP_VER = 0x62, OP_IF = 0x63, OP_NOTIF = 0x64,
    OP_VERIF = 0x65, OP_VERNOTIF = 0x66, OP_ELSE = 0x67, OP_ENDIF = 0x68,
    OP_VERIFY = 0x69, OP_RETURN = 0x6A, OP_TOALTSTACK = 0x6B,
    OP_FROMALTSTACK = 0x6C, OP_2DROP = 0x6D, OP_2DUP = 0x6E, OP_3DUP = 0x6F,
    OP_2OVER = 0x70, OP_2ROT = 0x71, OP_2SWAP = 0x72, OP_IFDUP = 0x73,
    OP_DEPTH = 0x74, OP_DROP = 0x75, OP_DUP = 0x76, OP_NIP = 0x77,
    OP_OVER = 0x78, OP_PICK = 0x79, OP_ROLL = 0x7A, OP_ROT = 0x7B,
    OP_SWAP = 0x7C, OP_TUCK = 0x7D, OP_CAT = 0x7E, OP_SUBSTR = 0x7F,
    OP_LEFT = 0x80, OP_RIGHT = 0x81, OP_SIZE = 0x82, OP_INVERT = 0x83,
    OP_AND = 0x84, OP_OR = 0x85, OP_XOR = 0x86, OP_EQUAL = 0x87,
    OP_EQUALVERIFY = 0x88, OP_RESERVED1 = 0x89, OP_RESERVED2 = 0x8A,
    OP_1ADD = 0x8B, OP_1SUB = 0x8C, OP_2MUL = 0x8D, OP_2DIV = 0x8E,
    OP_NEGATE = 0x8F, OP_ABS = 0x90, OP_NOT = 0x91, OP_0NOTEQUAL = 0x92,
    OP_ADD = 0x93, OP_SUB = 0x94, OP_MUL = 0x95, OP_DIV = 0x96,
    OP_MOD = 0x97, OP_LSHIFT = 0x98, OP_RSHIFT = 0x99, OP_BOOLAND = 0x9A,
    OP_BOOLOR = 0x9B, OP_NUMEQUAL = 0x9C, OP_NUMEQUALVERIFY = 0x9D,
    OP_NUMNOTEQUAL = 0x9E, OP_LESSTHAN = 0x9F, OP_GREATERTHAN = 0xA0,
    OP_LESSTHANOREQUAL = 0xA1, OP_GREATERTHANOREQUAL = 0xA2, OP_MIN = 0xA3,
    OP_MAX = 0xA4, OP_WITHIN = 0xA5, OP_RIPEMD160 = 0xA6, OP_SHA1 = 0xA7,
    OP_SHA256 = 0xA8, OP_HASH160 = 0xA9, OP_HASH256 = 0xAA,
    OP_CODESEPARATOR = 0xAB, OP_CHECKSIG = 0xAC, OP_CHECKSIGVERIFY = 0xAD,
    OP_CHECKMULTISIG = 0xAE, OP_CHECKMULTISIGVERIFY = 0xAF, OP_NOP1 = 0xB0,
    OP_CLTV = 0xB1, OP_CSV = 0xB2, OP_NOP4 = 0xB3, OP_NOP10 = 0xB9,
    OP_CHECKSIGADD = 0xBA,
};

// SigVersion (interpreter.h).
enum : int { SV_BASE = 0, SV_WITNESS_V0 = 1, SV_TAPROOT = 2, SV_TAPSCRIPT = 3 };

// Sighash types.
enum : int {
    SH_DEFAULT = 0, SH_ALL = 1, SH_NONE = 2, SH_SINGLE = 3,
    SH_ANYONECANPAY = 0x80, SH_OUTPUT_MASK = 3, SH_INPUT_MASK = 0x80,
};

constexpr u32 SEQUENCE_FINAL = 0xFFFFFFFFu;
constexpr u32 SEQ_DISABLE = 1u << 31;
constexpr u32 SEQ_TYPE = 1u << 22;
constexpr u32 SEQ_MASK = 0x0000FFFFu;

// Taproot control-block geometry (interpreter.h:214-219).
constexpr u8 TAPROOT_LEAF_MASK = 0xFE;
constexpr u8 TAPROOT_LEAF_TAPSCRIPT = 0xC0;
constexpr size_t TAPROOT_CONTROL_BASE_SIZE = 33;
constexpr size_t TAPROOT_CONTROL_NODE_SIZE = 32;
constexpr size_t TAPROOT_CONTROL_MAX_NODE_COUNT = 128;
constexpr size_t TAPROOT_CONTROL_MAX_SIZE =
    TAPROOT_CONTROL_BASE_SIZE + TAPROOT_CONTROL_NODE_SIZE * TAPROOT_CONTROL_MAX_NODE_COUNT;

// --------------------------------------------------------------------------
// Serialization

struct SerErr : std::runtime_error {
    using std::runtime_error::runtime_error;
};

struct Reader {
    const u8* data;
    size_t len;
    size_t pos = 0;

    Reader(const u8* d, size_t l) : data(d), len(l) {}

    const u8* read(size_t n) {
        if (pos + n > len) throw SerErr("read past end of data");
        const u8* p = data + pos;
        pos += n;
        return p;
    }
    u8 read_u8() { return *read(1); }
    u32 read_u32() {
        const u8* p = read(4);
        return (u32)p[0] | ((u32)p[1] << 8) | ((u32)p[2] << 16) | ((u32)p[3] << 24);
    }
    i32 read_i32() { return (i32)read_u32(); }
    u64 read_u64() {
        const u8* p = read(8);
        u64 v = 0;
        for (int i = 0; i < 8; i++) v |= (u64)p[i] << (8 * i);
        return v;
    }
    i64 read_i64() { return (i64)read_u64(); }
    u64 read_compact_size(bool range_check = true) {
        u8 first = read_u8();
        u64 size;
        if (first < 253) {
            size = first;
        } else if (first == 253) {
            const u8* p = read(2);
            size = (u64)p[0] | ((u64)p[1] << 8);
            if (size < 253) throw SerErr("non-canonical CompactSize");
        } else if (first == 254) {
            size = read_u32();
            if (size < 0x10000) throw SerErr("non-canonical CompactSize");
        } else {
            size = read_u64();
            if (size < 0x100000000ull) throw SerErr("non-canonical CompactSize");
        }
        if (range_check && size > SER_MAX_SIZE) throw SerErr("CompactSize exceeds MAX_SIZE");
        return size;
    }
    Bytes read_string() {
        u64 n = read_compact_size();
        const u8* p = read((size_t)n);
        return Bytes(p, p + n);
    }
};

inline void put_u32(Bytes& b, u32 v) {
    for (int i = 0; i < 4; i++) b.push_back(u8(v >> (8 * i)));
}
inline void put_i64(Bytes& b, i64 v) {
    u64 u = (u64)v;
    for (int i = 0; i < 8; i++) b.push_back(u8(u >> (8 * i)));
}
inline void put_compact_size(Bytes& b, u64 n) {
    if (n < 253) {
        b.push_back(u8(n));
    } else if (n <= 0xFFFF) {
        b.push_back(0xFD);
        b.push_back(u8(n));
        b.push_back(u8(n >> 8));
    } else if (n <= 0xFFFFFFFFull) {
        b.push_back(0xFE);
        put_u32(b, (u32)n);
    } else {
        b.push_back(0xFF);
        put_i64(b, (i64)n);
    }
}
inline void put_bytes(Bytes& b, const Bytes& s) {
    b.insert(b.end(), s.begin(), s.end());
}
inline void put_string(Bytes& b, const Bytes& s) {
    put_compact_size(b, s.size());
    put_bytes(b, s);
}

// --------------------------------------------------------------------------
// Transaction

struct NTxIn {
    u8 prevout_hash[32];
    u32 prevout_n;
    Bytes script_sig;
    u32 sequence;
    std::vector<Bytes> witness;
};

struct NTxOut {
    i64 value;
    Bytes spk;

    Bytes serialize() const {
        Bytes b;
        put_i64(b, value);
        put_string(b, spk);
        return b;
    }
};

struct Precomp {
    bool ready = false;
    bool spent_ready = false;
    bool bip143_ready = false;
    bool bip341_ready = false;
    u8 prevouts_single[32], sequences_single[32], outputs_single[32];
    u8 spent_amounts_single[32], spent_scripts_single[32];
    u8 hash_prevouts[32], hash_sequence[32], hash_outputs[32];
    std::vector<NTxOut> spent_outputs;
    u8 spent_digest[32] = {0};  // cache key over the registered prevouts
};

struct NTx {
    i32 version;
    std::vector<NTxIn> vin;
    std::vector<NTxOut> vout;
    u32 locktime;
    i64 ser_size;  // re-serialized size incl. witness (for the size check)
    Precomp precomp;

    bool has_witness() const {
        for (const auto& in : vin)
            if (!in.witness.empty()) return true;
        return false;
    }

    Bytes serialize(bool include_witness) const {
        bool use_wit = include_witness && has_witness();
        Bytes b;
        put_u32(b, (u32)version);
        if (use_wit) {
            b.push_back(0);
            b.push_back(1);
        }
        put_compact_size(b, vin.size());
        for (const auto& in : vin) {
            b.insert(b.end(), in.prevout_hash, in.prevout_hash + 32);
            put_u32(b, in.prevout_n);
            put_string(b, in.script_sig);
            put_u32(b, in.sequence);
        }
        put_compact_size(b, vout.size());
        for (const auto& out : vout) {
            put_i64(b, out.value);
            put_string(b, out.spk);
        }
        if (use_wit) {
            for (const auto& in : vin) {
                put_compact_size(b, in.witness.size());
                for (const auto& w : in.witness) put_string(b, w);
            }
        }
        put_u32(b, locktime);
        return b;
    }
};

// Exact mirror of UnserializeTransaction (transaction.h:187-224 /
// core/tx.py _deserialize_from). Throws SerErr. Vectors grow
// INCREMENTALLY (one entry per parsed element, each consuming >= 1 input
// byte) — never pre-sized from the attacker-claimed CompactSize, so a
// tiny malformed tx cannot demand a multi-GB allocation.
inline NTx* tx_parse_from(Reader& r) {
    auto tx = std::make_unique<NTx>();
    tx->version = r.read_i32();
    u8 flags = 0;
    u64 n_vin = r.read_compact_size();
    auto read_txin = [&](NTxIn& in) {
        const u8* h = r.read(32);
        std::memcpy(in.prevout_hash, h, 32);
        in.prevout_n = r.read_u32();
        in.script_sig = r.read_string();
        in.sequence = r.read_u32();
    };
    auto read_vin = [&](u64 n) {
        for (u64 i = 0; i < n; i++) {
            tx->vin.emplace_back();
            read_txin(tx->vin.back());
        }
    };
    auto read_vout = [&](u64 n) {
        for (u64 i = 0; i < n; i++) {
            tx->vout.emplace_back();
            tx->vout.back().value = r.read_i64();
            tx->vout.back().spk = r.read_string();
        }
    };
    read_vin(n_vin);
    if (tx->vin.empty()) {
        flags = r.read_u8();
        if (flags != 0) {
            read_vin(r.read_compact_size());
            read_vout(r.read_compact_size());
        }
    } else {
        read_vout(r.read_compact_size());
    }
    if (flags & 1) {
        flags ^= 1;
        bool any = false;
        for (auto& in : tx->vin) {
            u64 n = r.read_compact_size();
            for (u64 i = 0; i < n; i++) in.witness.push_back(r.read_string());
            if (n) any = true;
        }
        if (!any) throw SerErr("Superfluous witness record");
    }
    if (flags) throw SerErr("Unknown transaction optional data");
    tx->locktime = r.read_u32();
    tx->ser_size = (i64)tx->serialize(true).size();
    return tx.release();
}

inline NTx* tx_parse(const u8* data, size_t len) {
    Reader r(data, len);
    return tx_parse_from(r);
}

// --------------------------------------------------------------------------
// Script decoding / predicates (core/script.py twins)

struct Span {
    const u8* p;
    size_t n;
    u8 operator[](size_t i) const { return p[i]; }
    size_t size() const { return n; }
    Span sub(size_t off) const { return {p + off, n - off}; }
    Span sub(size_t off, size_t cnt) const { return {p + off, cnt}; }
};

inline Span span_of(const Bytes& b) { return {b.data(), b.size()}; }

// Decode one op; returns false on truncated push (opcode -> -1).
inline bool decode_op(Span s, size_t& pos, int& opcode, const u8** data,
                      size_t* dlen) {
    opcode = s[pos];
    pos += 1;
    *data = nullptr;
    *dlen = 0;
    if (opcode > OP_PUSHDATA4) return true;
    u64 size;
    if (opcode < OP_PUSHDATA1) {
        size = (u64)opcode;
    } else if (opcode == OP_PUSHDATA1) {
        if (pos + 1 > s.size()) return false;
        size = s[pos];
        pos += 1;
    } else if (opcode == OP_PUSHDATA2) {
        if (pos + 2 > s.size()) return false;
        size = (u64)s[pos] | ((u64)s[pos + 1] << 8);
        pos += 2;
    } else {
        if (pos + 4 > s.size()) return false;
        size = (u64)s[pos] | ((u64)s[pos + 1] << 8) | ((u64)s[pos + 2] << 16) |
               ((u64)s[pos + 3] << 24);
        pos += 4;
    }
    if (pos + size > s.size()) return false;
    *data = s.p + pos;
    *dlen = (size_t)size;
    pos += (size_t)size;
    return true;
}

inline Bytes push_data_enc(const Bytes& d) {
    Bytes out;
    size_t n = d.size();
    if (n < OP_PUSHDATA1) {
        out.push_back(u8(n));
    } else if (n <= 0xFF) {
        out.push_back(OP_PUSHDATA1);
        out.push_back(u8(n));
    } else if (n <= 0xFFFF) {
        out.push_back(OP_PUSHDATA2);
        out.push_back(u8(n));
        out.push_back(u8(n >> 8));
    } else {
        out.push_back(OP_PUSHDATA4);
        put_u32(out, (u32)n);
    }
    put_bytes(out, d);
    return out;
}

inline bool check_minimal_push(const u8* d, size_t n, int opcode) {
    if (n == 0) return opcode == OP_0;
    if (n == 1 && d[0] >= 1 && d[0] <= 16) return false;
    if (n == 1 && d[0] == 0x81) return false;
    if (n <= 75) return opcode == (int)n;
    if (n <= 255) return opcode == OP_PUSHDATA1;
    if (n <= 65535) return opcode == OP_PUSHDATA2;
    return true;
}

inline bool is_p2sh(const Bytes& s) {
    return s.size() == 23 && s[0] == OP_HASH160 && s[1] == 0x14 && s[22] == OP_EQUAL;
}

inline bool is_witness_program(const Bytes& s, int* version, Bytes* program) {
    if (s.size() < 4 || s.size() > 42) return false;
    if (s[0] != OP_0 && !(s[0] >= OP_1 && s[0] <= OP_16)) return false;
    if ((size_t)s[1] + 2 != s.size()) return false;
    *version = s[0] == OP_0 ? 0 : s[0] - OP_1 + 1;
    program->assign(s.begin() + 2, s.end());
    return true;
}

inline bool is_push_only(const Bytes& s) {
    Span sp = span_of(s);
    size_t pos = 0;
    while (pos < sp.size()) {
        int opcode;
        const u8* d;
        size_t dl;
        if (!decode_op(sp, pos, opcode, &d, &dl)) return false;
        if (opcode > OP_16) return false;
    }
    return true;
}

inline bool is_op_success(int op) {
    return op == 0x50 || op == 0x62 || (0x7E <= op && op <= 0x81) ||
           (0x83 <= op && op <= 0x86) || (0x89 <= op && op <= 0x8A) ||
           (0x8D <= op && op <= 0x8E) || (0x95 <= op && op <= 0x99) ||
           (0xBB <= op && op <= 0xFE);
}

// FindAndDelete (core/script.py find_and_delete semantics).
inline int find_and_delete(Bytes& script, const Bytes& needle) {
    if (needle.empty()) return 0;
    Bytes out;
    int n_found = 0;
    Span sp = span_of(script);
    size_t pos = 0, last = 0;
    while (pos < sp.size()) {
        out.insert(out.end(), sp.p + last, sp.p + pos);
        while (pos + needle.size() <= sp.size() &&
               std::memcmp(sp.p + pos, needle.data(), needle.size()) == 0) {
            pos += needle.size();
            n_found++;
        }
        last = pos;
        if (pos < sp.size()) {
            int opcode;
            const u8* d;
            size_t dl;
            if (!decode_op(sp, pos, opcode, &d, &dl)) break;
        } else {
            break;
        }
    }
    out.insert(out.end(), sp.p + last, sp.p + sp.size());
    if (n_found) script = out;
    return n_found;
}

// --------------------------------------------------------------------------
// CScriptNum

struct ScriptNumErr : std::runtime_error {
    using std::runtime_error::runtime_error;
};

inline i64 script_num_decode(const Bytes& d, bool require_minimal,
                             size_t max_size = 4) {
    if (d.size() > max_size) throw ScriptNumErr("script number overflow");
    if (require_minimal && !d.empty()) {
        if ((d.back() & 0x7F) == 0) {
            if (d.size() <= 1 || !(d[d.size() - 2] & 0x80))
                throw ScriptNumErr("non-minimally encoded script number");
        }
    }
    if (d.empty()) return 0;
    u64 result = 0;
    for (size_t i = 0; i < d.size(); i++) result |= (u64)d[i] << (8 * i);
    if (d.back() & 0x80) {
        result &= ~((u64)0x80 << (8 * (d.size() - 1)));
        return -(i64)result;
    }
    return (i64)result;
}

inline Bytes script_num_encode(i64 n) {
    Bytes out;
    if (n == 0) return out;
    bool negative = n < 0;
    u64 absvalue = negative ? (u64)(-(n + 1)) + 1 : (u64)n;
    while (absvalue) {
        out.push_back(u8(absvalue & 0xFF));
        absvalue >>= 8;
    }
    if (out.back() & 0x80) {
        out.push_back(negative ? 0x80 : 0x00);
    } else if (negative) {
        out.back() |= 0x80;
    }
    return out;
}

inline bool script_num_to_bool(const Bytes& d) {
    for (size_t i = 0; i < d.size(); i++) {
        if (d[i] != 0) return !(i == d.size() - 1 && d[i] == 0x80);
    }
    return false;
}

inline i64 clamp_int(i64 v) {
    if (v > 0x7FFFFFFFll) return 0x7FFFFFFFll;
    if (v < -0x80000000ll) return -0x80000000ll;
    return v;
}

// --------------------------------------------------------------------------
// Sighash

inline const TagMidstate& TAG_TAPSIGHASH() {
    static TagMidstate t("TapSighash");
    return t;
}
inline const TagMidstate& TAG_TAPLEAF() {
    static TagMidstate t("TapLeaf");
    return t;
}
inline const TagMidstate& TAG_TAPBRANCH() {
    static TagMidstate t("TapBranch");
    return t;
}
inline const TagMidstate& TAG_TAPTWEAK() {
    static TagMidstate t("TapTweak");
    return t;
}

// SerializeScriptCode (core/sighash.py _serialize_script_code semantics).
inline Bytes serialize_script_code(const Bytes& sc) {
    Span sp = span_of(sc);
    size_t n_codeseps = 0;
    size_t pos = 0;
    while (pos < sp.size()) {
        int opcode;
        const u8* d;
        size_t dl;
        if (!decode_op(sp, pos, opcode, &d, &dl)) break;
        if (opcode == OP_CODESEPARATOR) n_codeseps++;
    }
    Bytes out;
    put_compact_size(out, sc.size() - n_codeseps);
    size_t seg_start = 0;
    pos = 0;
    while (pos < sp.size()) {
        int opcode;
        const u8* d;
        size_t dl;
        size_t before = pos;
        if (!decode_op(sp, pos, opcode, &d, &dl)) {
            // truncated push: decoder consumed opcode/length bytes only;
            // write the segment up to that point, drop the tail.
            (void)before;
            out.insert(out.end(), sp.p + seg_start, sp.p + pos);
            return out;
        }
        if (opcode == OP_CODESEPARATOR) {
            out.insert(out.end(), sp.p + seg_start, sp.p + pos - 1);
            seg_start = pos;
        }
    }
    if (seg_start != sp.size()) out.insert(out.end(), sp.p + seg_start, sp.p + sp.size());
    return out;
}

inline void legacy_sighash(const Bytes& script_code, const NTx& tx, size_t n_in,
                           int hash_type, u8 out[32]) {
    bool anyone = (hash_type & SH_ANYONECANPAY) != 0;
    int base = hash_type & 0x1F;
    bool hash_single = base == SH_SINGLE;
    bool hash_none = base == SH_NONE;
    if (hash_single && n_in >= tx.vout.size()) {
        std::memset(out, 0, 32);
        out[0] = 1;
        return;
    }
    Bytes s;
    put_u32(s, (u32)tx.version);
    size_t n_inputs = anyone ? 1 : tx.vin.size();
    put_compact_size(s, n_inputs);
    for (size_t k = 0; k < n_inputs; k++) {
        size_t i = anyone ? n_in : k;
        const NTxIn& txin = tx.vin[i];
        s.insert(s.end(), txin.prevout_hash, txin.prevout_hash + 32);
        put_u32(s, txin.prevout_n);
        if (i != n_in) {
            put_compact_size(s, 0);
        } else {
            Bytes ssc = serialize_script_code(script_code);
            put_bytes(s, ssc);
        }
        if (i != n_in && (hash_single || hash_none)) {
            put_u32(s, 0);
        } else {
            put_u32(s, txin.sequence);
        }
    }
    size_t n_outputs;
    if (hash_none) n_outputs = 0;
    else if (hash_single) n_outputs = n_in + 1;
    else n_outputs = tx.vout.size();
    put_compact_size(s, n_outputs);
    for (size_t i = 0; i < n_outputs; i++) {
        if (hash_single && i != n_in) {
            put_i64(s, -1);
            put_compact_size(s, 0);
        } else {
            put_i64(s, tx.vout[i].value);
            put_string(s, tx.vout[i].spk);
        }
    }
    put_u32(s, tx.locktime);
    put_u32(s, (u32)(i32)hash_type);
    sha256d(s.data(), s.size(), out);
}

// Compute the tx-wide single-SHA aggregates + BIP143 doubles; spent
// aggregates when spent outputs are registered (interpreter.cpp:1422-1472).
inline void precompute(NTx& tx, const std::vector<NTxOut>* spent) {
    Precomp& pc = tx.precomp;
    pc = Precomp();
    pc.ready = true;
    // A prevout list is only usable when it has exactly one entry per
    // input (interpreter.cpp:1512 readiness contract); a wrong-length
    // list is ignored rather than indexed out of bounds.
    if (spent && spent->size() == tx.vin.size()) {
        pc.spent_outputs = *spent;
        pc.spent_ready = true;
    }
    bool uses143 = false, uses341 = false;
    for (size_t i = 0; i < tx.vin.size(); i++) {
        if (uses143 && uses341) break;
        if (!tx.vin[i].witness.empty()) {
            const Bytes* spk =
                pc.spent_ready ? &pc.spent_outputs[i].spk : nullptr;
            if (spk && spk->size() == 34 && (*spk)[0] == OP_1) uses341 = true;
            else uses143 = true;
        }
    }
    if (uses143 || uses341) {
        Bytes b;
        for (const auto& in : tx.vin) {
            b.insert(b.end(), in.prevout_hash, in.prevout_hash + 32);
            put_u32(b, in.prevout_n);
        }
        sha256(b.data(), b.size(), pc.prevouts_single);
        b.clear();
        for (const auto& in : tx.vin) put_u32(b, in.sequence);
        sha256(b.data(), b.size(), pc.sequences_single);
        b.clear();
        for (const auto& out : tx.vout) {
            put_i64(b, out.value);
            put_string(b, out.spk);
        }
        sha256(b.data(), b.size(), pc.outputs_single);
    }
    if (uses143) {
        sha256(pc.prevouts_single, 32, pc.hash_prevouts);
        sha256(pc.sequences_single, 32, pc.hash_sequence);
        sha256(pc.outputs_single, 32, pc.hash_outputs);
        pc.bip143_ready = true;
    }
    if (uses341 && pc.spent_ready) {
        Bytes b;
        for (const auto& out : pc.spent_outputs) put_i64(b, out.value);
        sha256(b.data(), b.size(), pc.spent_amounts_single);
        b.clear();
        for (const auto& out : pc.spent_outputs) put_string(b, out.spk);
        sha256(b.data(), b.size(), pc.spent_scripts_single);
        pc.bip341_ready = true;
    }
}

inline void bip143_sighash(const Bytes& script_code, const NTx& tx, size_t n_in,
                           int hash_type, i64 amount, u8 out[32]) {
    const Precomp& pc = tx.precomp;
    bool cacheready = pc.ready && pc.bip143_ready;
    u8 hash_prevouts[32] = {0}, hash_sequence[32] = {0}, hash_outputs[32] = {0};
    int base = hash_type & 0x1F;
    if (!(hash_type & SH_ANYONECANPAY)) {
        if (cacheready) {
            std::memcpy(hash_prevouts, pc.hash_prevouts, 32);
        } else {
            Bytes b;
            for (const auto& in : tx.vin) {
                b.insert(b.end(), in.prevout_hash, in.prevout_hash + 32);
                put_u32(b, in.prevout_n);
            }
            sha256d(b.data(), b.size(), hash_prevouts);
        }
    }
    if (!(hash_type & SH_ANYONECANPAY) && base != SH_SINGLE && base != SH_NONE) {
        if (cacheready) {
            std::memcpy(hash_sequence, pc.hash_sequence, 32);
        } else {
            Bytes b;
            for (const auto& in : tx.vin) put_u32(b, in.sequence);
            sha256d(b.data(), b.size(), hash_sequence);
        }
    }
    if (base != SH_SINGLE && base != SH_NONE) {
        if (cacheready) {
            std::memcpy(hash_outputs, pc.hash_outputs, 32);
        } else {
            Bytes b;
            for (const auto& out : tx.vout) {
                put_i64(b, out.value);
                put_string(b, out.spk);
            }
            sha256d(b.data(), b.size(), hash_outputs);
        }
    } else if (base == SH_SINGLE && n_in < tx.vout.size()) {
        Bytes b = tx.vout[n_in].serialize();
        sha256d(b.data(), b.size(), hash_outputs);
    }
    Bytes s;
    put_u32(s, (u32)tx.version);
    s.insert(s.end(), hash_prevouts, hash_prevouts + 32);
    s.insert(s.end(), hash_sequence, hash_sequence + 32);
    s.insert(s.end(), tx.vin[n_in].prevout_hash, tx.vin[n_in].prevout_hash + 32);
    put_u32(s, tx.vin[n_in].prevout_n);
    put_string(s, script_code);
    put_i64(s, amount);
    put_u32(s, tx.vin[n_in].sequence);
    s.insert(s.end(), hash_outputs, hash_outputs + 32);
    put_u32(s, tx.locktime);
    put_u32(s, (u32)(i32)hash_type);
    sha256d(s.data(), s.size(), out);
}

// Returns false on invalid hash type / SINGLE out of range.
inline bool bip341_sighash(const NTx& tx, size_t n_in, int hash_type,
                           int sigversion, bool annex_present,
                           const u8* annex_hash, const Bytes& tapleaf_hash,
                           u32 codeseparator_pos, u8 out[32]) {
    const Precomp& pc = tx.precomp;
    int ext_flag = sigversion == SV_TAPSCRIPT ? 1 : 0;
    Bytes s;
    s.push_back(0);  // epoch
    int output_type = hash_type == SH_DEFAULT ? SH_ALL : (hash_type & SH_OUTPUT_MASK);
    int input_type = hash_type & SH_INPUT_MASK;
    if (!(hash_type <= 0x03 || (hash_type >= 0x81 && hash_type <= 0x83)))
        return false;
    s.push_back(u8(hash_type));
    put_u32(s, (u32)tx.version);
    put_u32(s, tx.locktime);
    if (input_type != SH_ANYONECANPAY) {
        s.insert(s.end(), pc.prevouts_single, pc.prevouts_single + 32);
        s.insert(s.end(), pc.spent_amounts_single, pc.spent_amounts_single + 32);
        s.insert(s.end(), pc.spent_scripts_single, pc.spent_scripts_single + 32);
        s.insert(s.end(), pc.sequences_single, pc.sequences_single + 32);
    }
    if (output_type == SH_ALL)
        s.insert(s.end(), pc.outputs_single, pc.outputs_single + 32);
    u8 spend_type = u8((ext_flag << 1) + (annex_present ? 1 : 0));
    s.push_back(spend_type);
    if (input_type == SH_ANYONECANPAY) {
        s.insert(s.end(), tx.vin[n_in].prevout_hash, tx.vin[n_in].prevout_hash + 32);
        put_u32(s, tx.vin[n_in].prevout_n);
        Bytes so = pc.spent_outputs[n_in].serialize();
        put_bytes(s, so);
        put_u32(s, tx.vin[n_in].sequence);
    } else {
        put_u32(s, (u32)n_in);
    }
    if (annex_present) s.insert(s.end(), annex_hash, annex_hash + 32);
    if (output_type == SH_SINGLE) {
        if (n_in >= tx.vout.size()) return false;
        Bytes ob = tx.vout[n_in].serialize();
        u8 oh[32];
        sha256(ob.data(), ob.size(), oh);
        s.insert(s.end(), oh, oh + 32);
    }
    if (sigversion == SV_TAPSCRIPT) {
        s.insert(s.end(), tapleaf_hash.begin(), tapleaf_hash.end());
        s.push_back(0);  // key_version
        put_u32(s, codeseparator_pos);
    }
    TAG_TAPSIGHASH().hash(s.data(), s.size(), out);
    return true;
}

// --------------------------------------------------------------------------
// Checker with the deferral seam (models/batch.py DeferringSignatureChecker
// + core/interpreter.py TransactionSignatureChecker semantics).

struct Record {
    int kind;  // 0 ecdsa, 1 schnorr, 2 tweak
    int parity;
    Bytes p0, p1, p2;  // ecdsa: pubkey|sig|msg; schnorr: pk32|sig64|msg;
                       // tweak: q32|internal32|tweak32
};

struct Session {
    std::map<std::string, bool> known;
    std::vector<Record> records;
    // --- Index-mode (session-resident uniq protocol) -----------------
    // The batch driver's fast path: instead of draining full record
    // bytes to Python, deduping there, and shipping them back for
    // digesting/lane-prep/publishing, the session keeps ONE deduped
    // check list (`uniq`, discovery order) and each verify call emits
    // only int32 indices into it (`rec_idx`). Lanes, cache digests and
    // verdict publication all read uniq in place — zero byte round-trips
    // across the ctypes bridge (the round-3 profile showed ~200 ms of a
    // 3.2k-input block replay in exactly that shuffling).
    bool index_mode = false;
    std::vector<Record> uniq;
    std::vector<std::string> uniq_keys;  // parallel: known-map key per uniq
    std::unordered_map<std::string, i32> uniq_seen;  // key -> uniq index
    std::vector<i32> rec_idx;  // per-call flat index stream
    // Read-only oracle for worker-scratch sessions (checkqueue.h analogue:
    // the threaded interpretation shards share the main session's known
    // map; scratch sessions collect records locally and merge serially).
    const Session* oracle = nullptr;

    const std::map<std::string, bool>& known_view() const {
        return oracle ? oracle->known : known;
    }

    // Record an oracle miss in index mode: dedup into uniq, emit index.
    void index_record(std::string&& k, int kind, int parity, const Bytes& a,
                      const Bytes& b, const Bytes& c) {
        auto ins = uniq_seen.try_emplace(std::move(k), (i32)uniq.size());
        if (ins.second) {
            uniq.push_back(Record{kind, parity, a, b, c});
            uniq_keys.push_back(ins.first->first);
        }
        rec_idx.push_back(ins.first->second);
    }
    // Speculative CHECKMULTISIG pairings: every (sig, key) pair the cursor
    // walk could reach (key-index minus sig-index in [0, nkeys-nsigs]) is
    // pre-recorded here so ONE device dispatch answers every oracle read a
    // re-interpretation can make — misaligned multisig resolves without a
    // second host->device round-trip. Kept apart from `records` so the
    // optimistic-verdict judgment stays exact (a false speculative pair
    // must not reject a verdict whose own checks all held).
    std::vector<Record> spec;
    std::set<std::string> spec_seen;
    int unknown = 0;

    static std::string key(int kind, int parity, const Bytes& a, const Bytes& b,
                           const Bytes& c) {
        std::string k;
        k.push_back(char(kind));
        k.push_back(char(parity));
        auto add = [&](const Bytes& v) {
            u64 n = v.size();
            for (int i = 0; i < 8; i++) k.push_back(char(u8(n >> (8 * i))));
            k.append(reinterpret_cast<const char*>(v.data()), v.size());
        };
        add(a);
        add(b);
        add(c);
        return k;
    }
};

struct ExecData {
    bool annex_present = false;
    u8 annex_hash[32] = {0};
    bool tapleaf_hash_init = false;
    Bytes tapleaf_hash;
    u32 codeseparator_pos = 0xFFFFFFFF;
    bool validation_weight_left_init = false;
    i64 validation_weight_left = 0;
};

enum : int { MODE_DEFER = 0, MODE_EXACT = 1 };

struct Checker {
    const NTx* tx;
    size_t n_in;
    i64 amount;
    int mode;
    Session* sess;  // used in MODE_DEFER

    // raw curve resolution: oracle -> record-optimistic (defer) or native
    // verify (exact)
    bool resolve(int kind, int parity, const Bytes& a, const Bytes& b,
                 const Bytes& c) {
        if (mode == MODE_EXACT) {
            if (kind == 0)
                return verify_ecdsa(a.data(), a.size(), b.data(), b.size(), c.data());
            if (kind == 1) return verify_schnorr(a.data(), b.data(), c.data());
            return tweak_add_check(a.data(), parity, b.data(), c.data());
        }
        std::string k = Session::key(kind, parity, a, b, c);
        const auto& known = sess->known_view();
        auto it = known.find(k);
        if (it != known.end()) return it->second;
        sess->unknown++;
        if (sess->index_mode)
            sess->index_record(std::move(k), kind, parity, a, b, c);
        else
            sess->records.push_back(Record{kind, parity, a, b, c});
        return true;
    }

    // Structural early-false gates shared by check and speculate: a sig/key
    // failing these never reaches the curve, so there is nothing to defer.
    static bool pubkey_plausible(const Bytes& pubkey) {
        if (pubkey.empty()) return false;
        u8 p0 = pubkey[0];
        if (p0 == 2 || p0 == 3) return pubkey.size() == 33;
        if (p0 == 4 || p0 == 6 || p0 == 7) return pubkey.size() == 65;
        return false;
    }

    static bool ec_check_plausible(const Bytes& sig, const Bytes& pubkey) {
        return !sig.empty() && pubkey_plausible(pubkey);
    }

    void ecdsa_sighash(const Bytes& sig, const Bytes& script_code,
                       int sigversion, Bytes* sig_body, Bytes* msg) {
        int hash_type = sig.back();
        sig_body->assign(sig.begin(), sig.end() - 1);
        u8 sighash[32];
        if (sigversion == SV_WITNESS_V0) {
            bip143_sighash(script_code, *tx, n_in, hash_type, amount, sighash);
        } else {
            legacy_sighash(script_code, *tx, n_in, hash_type, sighash);
        }
        msg->assign(sighash, sighash + 32);
    }

    bool check_ecdsa_signature(const Bytes& sig, const Bytes& pubkey,
                               const Bytes& script_code, int sigversion) {
        if (!ec_check_plausible(sig, pubkey)) return false;
        Bytes sig_body, msg;
        ecdsa_sighash(sig, script_code, sigversion, &sig_body, &msg);
        return resolve(0, 0, pubkey, sig_body, msg);
    }

    // Speculative CHECKMULTISIG pre-recording, split so the sighash (a
    // function of the sig's hash_type only, not the key) is computed ONCE
    // per sig: prep yields (sig_body, msg), then record per reachable key.
    bool speculate_ecdsa_prep(const Bytes& sig, const Bytes& script_code,
                              int sigversion, Bytes* sig_body, Bytes* msg) {
        if (mode != MODE_DEFER || !sess) return false;
        if (sig.empty()) return false;
        ecdsa_sighash(sig, script_code, sigversion, sig_body, msg);
        return true;
    }

    void speculate_ecdsa_record(const Bytes& pubkey, const Bytes& sig_body,
                                const Bytes& msg) {
        if (!pubkey_plausible(pubkey)) return;
        std::string k = Session::key(0, 0, pubkey, sig_body, msg);
        if (sess->known_view().count(k)) return;
        if (sess->index_mode) {
            // Resolve-only: dedup into uniq WITHOUT emitting a rec_idx
            // entry, so a speculative pair can never affect an
            // optimistic verdict (same contract as the spec vector).
            auto ins = sess->uniq_seen.try_emplace(std::move(k),
                                                   (i32)sess->uniq.size());
            if (ins.second) {
                sess->uniq.push_back(Record{0, 0, pubkey, sig_body, msg});
                sess->uniq_keys.push_back(ins.first->first);
            }
            return;
        }
        if (!sess->spec_seen.insert(k).second) return;
        sess->spec.push_back(Record{0, 0, pubkey, sig_body, msg});
    }

    // returns ok; on hard failure sets *err
    bool check_schnorr_signature(const Bytes& sig_in, const Bytes& pubkey,
                                 int sigversion, ExecData& execdata, i32* err) {
        Bytes sig = sig_in;
        if (sig.size() != 64 && sig.size() != 65) {
            *err = SE_SCHNORR_SIG_SIZE;
            return false;
        }
        int hash_type = SH_DEFAULT;
        if (sig.size() == 65) {
            hash_type = sig.back();
            sig.pop_back();
            if (hash_type == SH_DEFAULT) {
                *err = SE_SCHNORR_SIG_HASHTYPE;
                return false;
            }
        }
        u8 sighash[32];
        if (!bip341_sighash(*tx, n_in, hash_type, sigversion,
                            execdata.annex_present, execdata.annex_hash,
                            execdata.tapleaf_hash, execdata.codeseparator_pos,
                            sighash)) {
            *err = SE_SCHNORR_SIG_HASHTYPE;
            return false;
        }
        Bytes msg(sighash, sighash + 32);
        if (!resolve(1, 0, pubkey, sig, msg)) {
            *err = SE_SCHNORR_SIG;
            return false;
        }
        return true;
    }

    bool check_lock_time(i64 lock_time) {
        i64 tx_lock = (i64)tx->locktime;
        if (!((tx_lock < LOCKTIME_THRESHOLD && lock_time < LOCKTIME_THRESHOLD) ||
              (tx_lock >= LOCKTIME_THRESHOLD && lock_time >= LOCKTIME_THRESHOLD)))
            return false;
        if (lock_time > tx_lock) return false;
        if (tx->vin[n_in].sequence == SEQUENCE_FINAL) return false;
        return true;
    }

    bool check_sequence(i64 sequence) {
        u32 tx_sequence = tx->vin[n_in].sequence;
        if ((u32)tx->version < 2) return false;
        if (tx_sequence & SEQ_DISABLE) return false;
        u32 mask = SEQ_TYPE | SEQ_MASK;
        u32 tx_masked = tx_sequence & mask;
        u32 seq_masked = (u32)sequence & mask;
        if (!((tx_masked < SEQ_TYPE && seq_masked < SEQ_TYPE) ||
              (tx_masked >= SEQ_TYPE && seq_masked >= SEQ_TYPE)))
            return false;
        if (seq_masked > tx_masked) return false;
        return true;
    }

    bool verify_taproot_tweak(const Bytes& q, int parity, const Bytes& p,
                              const Bytes& t) {
        return resolve(2, parity, q, p, t);
    }
};

// --------------------------------------------------------------------------
// Encoding checks (interpreter.cpp:107-227 twins; byte-level only).

inline bool is_valid_signature_encoding(const Bytes& sig) {
    if (sig.size() < 9 || sig.size() > 73) return false;
    if (sig[0] != 0x30) return false;
    if (sig[1] != sig.size() - 3) return false;
    size_t lenR = sig[3];
    if (5 + lenR >= sig.size()) return false;
    size_t lenS = sig[5 + lenR];
    if (lenR + lenS + 7 != sig.size()) return false;
    if (sig[2] != 0x02) return false;
    if (lenR == 0) return false;
    if (sig[4] & 0x80) return false;
    if (lenR > 1 && sig[4] == 0x00 && !(sig[5] & 0x80)) return false;
    if (sig[lenR + 4] != 0x02) return false;
    if (lenS == 0) return false;
    if (sig[lenR + 6] & 0x80) return false;
    if (lenS > 1 && sig[lenR + 6] == 0x00 && !(sig[lenR + 7] & 0x80)) return false;
    return true;
}

inline bool is_low_der_signature(const Bytes& sig) {
    // strict-DER already checked by the caller; parse (r, s) laxly and
    // test s <= n/2 (pubkey.cpp:301-308 CheckLowS).
    Sc r, s;
    if (!parse_der_lax(sig.data(), sig.size() - 1, &r, &s)) return false;
    return !sc_is_high(s);
}

inline bool is_compressed_or_uncompressed_pubkey(const Bytes& pk) {
    if (pk.size() < 33) return false;
    if (pk[0] == 0x04) return pk.size() == 65;
    if (pk[0] == 0x02 || pk[0] == 0x03) return pk.size() == 33;
    return false;
}

inline bool is_compressed_pubkey(const Bytes& pk) {
    return pk.size() == 33 && (pk[0] == 0x02 || pk[0] == 0x03);
}

inline i32 check_signature_encoding(const Bytes& sig, u32 flags) {
    if (sig.empty()) return SE_OK;
    if (flags & (F_DERSIG | F_LOW_S | F_STRICTENC)) {
        if (!is_valid_signature_encoding(sig)) return SE_SIG_DER;
    }
    if (flags & F_LOW_S) {
        if (!is_valid_signature_encoding(sig)) return SE_SIG_DER;
        if (!is_low_der_signature(sig)) return SE_SIG_HIGH_S;
    }
    if (flags & F_STRICTENC) {
        int hash_type = sig.back() & ~0x80;
        if (hash_type < 1 || hash_type > 3) return SE_SIG_HASHTYPE;
    }
    return SE_OK;
}

inline i32 check_pubkey_encoding(const Bytes& pk, u32 flags, int sigversion) {
    if ((flags & F_STRICTENC) && !is_compressed_or_uncompressed_pubkey(pk))
        return SE_PUBKEYTYPE;
    if ((flags & F_WITNESS_PUBKEYTYPE) && sigversion == SV_WITNESS_V0 &&
        !is_compressed_pubkey(pk))
        return SE_WITNESS_PUBKEYTYPE;
    return SE_OK;
}

}  // namespace nat
